"""Fleet control plane (PR 19).

Covers the four coupled mechanisms end to end: SLO-aware admission
(deadline-aware would-miss shedding — the victim is the request that
WILL miss its target, never simply the newest; miss/headroom families
lockstep with stats()), tenant fair-share (a 10x storm cannot starve
the quiet tenant: WFQ interleaving bounds its wait, the door cap sheds
the over-share tenant with ``tenant_over``, and
``gateway_tenant_cost_bytes{tenant=}`` moves in lockstep between
Prometheus and stats()), the :class:`FleetController` decision loop
(router weight steering, group/restore sizing, elastic spawn/retire —
every setpoint CHANGE lands the ``gateway_fleet_decisions_total``
counter, the stats() mirror, and a ``fleet`` flight event), and the
elastic replica lifecycle on a REAL fleet (spawn serves traffic,
retire drains with zero lost requests and byte-identical text, the
draining state is distinct from wedged in ``/readyz`` and the router
skips it for new work).
"""

import asyncio
import time

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.server.admission import (
    AdmissionConfig,
    AdmissionController,
    QueueFullError,
)
from llm_consensus_tpu.server.metrics import REGISTRY, MetricsRegistry
from llm_consensus_tpu.serving import flight as _flight
from llm_consensus_tpu.serving.continuous import ContinuousConfig
from llm_consensus_tpu.serving.fleet import (
    FleetBackend,
    FleetConfig,
    ReplicaSet,
)
from llm_consensus_tpu.serving.fleet_control import (
    FleetControlConfig,
    FleetController,
)

CFG = get_config("test-tiny")

_HEADER = "Panel shared header for every persona, forty ch: "

_FCFG = dict(
    max_slots=2,
    page_size=16,
    n_pages=32,
    pages_per_seq=8,
    max_new_tokens=4,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
    host_cache_bytes=64 << 20,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _fleet(params, replicas=2, fleet_kw=None, **cfg_over):
    return ReplicaSet(
        CFG,
        params,
        config=ContinuousConfig(**{**_FCFG, **cfg_over}),
        fleet=FleetConfig(replicas=replicas, **(fleet_kw or {})),
    )


# ---------------------------------------------------------------------------
# SLO-aware admission: would-miss victim selection + miss accounting
# ---------------------------------------------------------------------------


def test_would_miss_shed_victims_doomed_not_newest():
    """At a full queue the shed victim is the queued request that WILL
    miss its SLO (negative predicted slack), never simply the newest
    arrival — the doomed request 429s with ``slo_miss`` and the
    newcomer takes its place."""

    async def main():
        reg = MetricsRegistry()
        c = AdmissionController(
            AdmissionConfig(
                max_queue=2,
                max_inflight=1,
                slo_classes={"fast": 0.05, "slow": 60.0},
            ),
            registry=reg,
        )
        gate = asyncio.Event()

        async def wait():
            await gate.wait()

        blocker = asyncio.create_task(c.submit(wait, slo="slow"))
        await asyncio.sleep(0.02)  # blocker holds the one in-flight slot
        doomed = asyncio.create_task(c.submit(wait, slo="fast"))
        healthy = asyncio.create_task(c.submit(wait, slo="slow"))
        # Let the fast-class request age past its 50ms target while
        # queued: its predicted slack goes negative.
        await asyncio.sleep(0.12)
        # Queue full (bound 2). The newcomer has 60s of slack; the
        # doomed fast request is shed in its favor.
        newcomer = asyncio.create_task(c.submit(wait, slo="slow"))
        await asyncio.sleep(0.02)
        assert doomed.done()
        err = doomed.exception()
        assert isinstance(err, QueueFullError) and err.slo_miss is True
        # The newest arrival was ADMITTED, the healthy one untouched.
        assert not newcomer.done() and not healthy.done()
        s = c.stats()
        assert s["slo_sheds"] == 1
        assert s["slo_miss"] == {"fast": 1}
        # Prometheus lockstep (per-instance registry).
        fam = reg.get("gateway_slo_shed_total")
        assert fam.labels(**{"class": "fast"}).value == 1.0
        fam = reg.get("gateway_slo_miss_total")
        assert fam.labels(**{"class": "fast"}).value == 1.0
        gate.set()
        await asyncio.gather(
            blocker, healthy, newcomer, return_exceptions=True
        )

    asyncio.run(main())


def test_slo_miss_at_dispatch_and_headroom_lockstep():
    """A dispatch whose queue wait exceeded its class target is a
    recorded miss; every SLO-tagged admission observes predicted
    headroom — both families lockstep with stats(). Unknown classes
    are rejected at the door (the gateway's 400)."""

    async def main():
        reg = MetricsRegistry()
        c = AdmissionController(
            AdmissionConfig(
                max_inflight=1,
                slo_classes={"tight": 0.01},
                default_slo_class="tight",
            ),
            registry=reg,
        )
        with pytest.raises(ValueError, match="unknown slo class"):
            await c.submit(lambda: asyncio.sleep(0), slo="nope")
        gate = asyncio.Event()

        async def wait():
            await gate.wait()

        blocker = asyncio.create_task(c.submit(wait))
        await asyncio.sleep(0.02)
        # Defaulted into the tight class; waits > 10ms while queued.
        late = asyncio.create_task(c.submit(wait))
        await asyncio.sleep(0.05)
        gate.set()
        await asyncio.gather(blocker, late)
        s = c.stats()
        assert s["slo_miss"].get("tight", 0) >= 1
        assert s["slo_headroom_count"] == 2
        fam = reg.get("gateway_slo_miss_total")
        assert fam.labels(**{"class": "tight"}).value == float(
            s["slo_miss"]["tight"]
        )
        h = reg.get("gateway_slo_headroom_seconds")
        assert h.count == 2
        assert h.sum == pytest.approx(s["slo_headroom_sum"])

    asyncio.run(main())


# ---------------------------------------------------------------------------
# Tenant fair-share: the 10x storm (satellite 3) + door cap
# ---------------------------------------------------------------------------


def test_tenant_storm_quiet_tenant_completes_bounded():
    """One tenant floods at 10x the quiet tenant's rate. WFQ dispatch
    interleaves: every quiet request completes with a bounded number of
    storm dispatches ahead of it (not behind the whole flood), and
    ``gateway_tenant_cost_bytes{tenant=}`` moves in lockstep between
    Prometheus and stats()."""

    async def main():
        reg = MetricsRegistry()
        c = AdmissionController(
            AdmissionConfig(
                max_queue=64,
                max_inflight=1,
                tenant_fair_share=True,
            ),
            registry=reg,
        )
        order: list[str] = []

        def thunk(tag):
            async def run():
                order.append(tag)

            return run

        hold = asyncio.Event()

        async def blocker_run():
            await hold.wait()

        # Hold the dispatcher so the whole storm queues before any
        # dispatch happens — worst case for the quiet tenant.
        blocker = asyncio.create_task(
            c.submit(blocker_run, tenant="storm")
        )
        await asyncio.sleep(0.02)
        storm = [
            asyncio.create_task(
                c.submit(thunk(f"s{i}"), tenant="storm")
            )
            for i in range(40)
        ]
        await asyncio.sleep(0)
        quiet = [
            asyncio.create_task(
                c.submit(thunk(f"q{i}"), tenant="quiet")
            )
            for i in range(4)
        ]
        await asyncio.sleep(0.02)
        hold.set()
        await asyncio.gather(blocker, *storm, *quiet)
        # Every quiet request completed ...
        qpos = [order.index(f"q{i}") for i in range(4)]
        # ... and each dispatched interleaved near the front: the k-th
        # quiet request admits at WFQ tag k+1, tying the k-th storm
        # request instead of queueing behind all 40. Bound with slack.
        assert max(qpos) < 12, (qpos, order[:16])
        s = c.stats()
        assert s["tenant_cost_bytes"] == {"storm": 41.0, "quiet": 4.0}
        fam = reg.get("gateway_tenant_cost_bytes")
        for tenant, total in s["tenant_cost_bytes"].items():
            assert fam.labels(tenant=tenant).value == total

    asyncio.run(main())


def test_tenant_over_share_shed_at_door():
    """Under contention (another tenant queued) a tenant past its
    weighted admitted-cost share is shed at the door with
    ``tenant_over`` — and the overflow hook is never consulted for it
    (preempting backend capacity cannot fix unfairness). Without
    contention the cap is inert (work-conserving)."""

    async def main():
        reg = MetricsRegistry()
        c = AdmissionController(
            AdmissionConfig(
                max_queue=64,
                max_inflight=1,
                tenant_fair_share=True,
                fair_share_slack=1.1,
            ),
            registry=reg,
        )
        hook_calls = []
        c.overflow_hook = lambda: hook_calls.append(1) or True
        hold = asyncio.Event()

        async def wait():
            await hold.wait()

        # No contention: the greedy tenant admits freely.
        tasks = [
            asyncio.create_task(c.submit(wait, tenant="greedy"))
            for _ in range(10)
        ]
        await asyncio.sleep(0.02)
        # Contention arrives: one quiet request queues.
        tasks.append(
            asyncio.create_task(c.submit(wait, tenant="other"))
        )
        await asyncio.sleep(0.02)
        # Greedy is far past a 50% fair share of the recent window.
        with pytest.raises(QueueFullError) as ei:
            await c.submit(wait, tenant="greedy")
        assert ei.value.tenant_over is True
        assert not hook_calls, "overflow hook consulted for fairness shed"
        s = c.stats()
        assert s["tenant_sheds"] == {"greedy": 1}
        fam = reg.get("gateway_tenant_shed_total")
        assert fam.labels(tenant="greedy").value == 1.0
        hold.set()
        await asyncio.gather(*tasks, return_exceptions=True)

    asyncio.run(main())


# ---------------------------------------------------------------------------
# FleetController decision loop (fake fleet: fast, deterministic)
# ---------------------------------------------------------------------------


class _FakeController:
    def __init__(self):
        self.restore_debt_bytes = 0.0
        self.caps = []

    def steer_restore_cap(self, cap):
        self.caps.append(cap)


class _FakeBatcher:
    def __init__(self):
        self.load = 0.0
        self.depth = 0
        self.active = 0
        self.group_caps = []
        self.controller = _FakeController()

    def waiting_depth(self):
        return self.depth

    def active_requests(self):
        return self.active

    def load_cost(self):
        return self.load

    def request_group_cap(self, n):
        self.group_caps.append(n)


class _FakeRouter:
    def __init__(self):
        self.weight_sets = []

    def set_weights(self, w):
        self.weight_sets.append(list(w))


class _FakeFleetConfig:
    max_slots = 4
    host_cache_bytes = 1000


class _FakeFleet:
    def __init__(self, n=2):
        self.batchers = [_FakeBatcher() for _ in range(n)]
        self.roles = ["mixed"] * n
        self.states = ["serving"] * n
        self.router = _FakeRouter()
        self.config = _FakeFleetConfig()
        self.store = object()
        self.retired = []

    def serving_indices(self):
        return [i for i, s in enumerate(self.states) if s == "serving"]

    def spawn_replica(self):
        self.batchers.append(_FakeBatcher())
        self.roles.append("mixed")
        self.states.append("serving")
        return len(self.batchers) - 1

    def retire_replica(self, idx, wait_s=60.0):
        self.states[idx] = "retired"
        self.retired.append(idx)
        return {"replica": idx}


def _decision_values():
    fam = REGISTRY.get("gateway_fleet_decisions_total")
    return {
        d: fam.labels(decision=d).value
        for d in (
            "router_weights",
            "group_cap",
            "restore_cap",
            "spawn",
            "retire",
        )
    }


def test_fleet_controller_decisions_counters_and_flight_lockstep():
    """One synchronous pass over every decision kind: router weights
    from relative load, group cap + restore cap from queue pressure,
    elastic spawn from sustained depth, elastic retire from sustained
    idleness. Each setpoint CHANGE moves gateway_fleet_decisions_total,
    the stats() mirror, and a ``fleet`` flight event — steady-state
    ticks move none of them."""
    rs = _FakeFleet(2)
    ctrl = FleetController(
        rs,
        FleetControlConfig(
            slo_classes={"interactive": 2.0},
            elastic_min=2,
            elastic_max=3,
            spawn_depth=2.0,
            spawn_sustain_ticks=2,
            retire_idle_ticks=2,
        ),
    )
    base = _decision_values()
    fleet_events0 = sum(
        1 for e in _flight.flight_recorder().events() if e.kind == "fleet"
    )

    # Tick 1: unequal load, saturating depth, zero restore debt.
    rs.batchers[0].load, rs.batchers[1].load = 1000.0, 3000.0
    rs.batchers[0].depth, rs.batchers[1].depth = 6, 4
    ctrl.tick()
    assert rs.router.weight_sets[-1] == [0.5, 1.5]
    # Pressure 10/8 >= 1.0: group cap widens to max_slots on every
    # serving batcher; restore batches narrow under queue pressure.
    assert rs.batchers[0].group_caps[-1] == 4
    assert rs.batchers[1].group_caps[-1] == 4
    assert rs.batchers[0].controller.caps[-1] == 2
    d1 = _decision_values()
    assert d1["router_weights"] - base["router_weights"] == 1
    assert d1["group_cap"] - base["group_cap"] == 1
    assert d1["restore_cap"] - base["restore_cap"] == 1

    # Tick 2: same signals — gauges refresh, decisions do NOT move
    # (spawn streak hits its sustain threshold and fires instead).
    ctrl.tick()
    d2 = _decision_values()
    assert d2["router_weights"] == d1["router_weights"]
    assert d2["group_cap"] == d1["group_cap"]
    assert len(rs.router.weight_sets) == 2  # refreshed every tick
    assert d2["spawn"] - base["spawn"] == 1
    assert len(rs.batchers) == 3 and rs.states[2] == "serving"

    # Heavy restore debt clears the narrowed cap.
    for b in rs.batchers:
        b.controller.restore_debt_bytes = 200.0  # 600/1000 >= 0.25
    ctrl.tick()
    d3 = _decision_values()
    assert d3["restore_cap"] - d2["restore_cap"] == 1
    assert rs.batchers[0].controller.caps[-1] is None

    # Fleet goes idle: after the sustain window the controller retires
    # the highest-index serving replica back down to elastic_min.
    for b in rs.batchers:
        b.load, b.depth, b.active = 0.0, 0, 0
        b.controller.restore_debt_bytes = 0.0
    ctrl.tick()
    ctrl.tick()
    d4 = _decision_values()
    assert d4["retire"] - base["retire"] == 1
    assert rs.retired == [2] and rs.states[2] == "retired"
    # At elastic_min: further idle ticks retire nothing.
    ctrl.tick()
    ctrl.tick()
    assert _decision_values()["retire"] == d4["retire"]

    # stats() mirror lockstep with the Prometheus deltas.
    s = ctrl.stats()
    final = _decision_values()
    for d in ("router_weights", "group_cap", "restore_cap", "spawn", "retire"):
        assert s[f"fleet_decisions_{d}"] == final[d] - base[d], d
    # Every decision landed one ``fleet`` flight event.
    fleet_events = [
        e for e in _flight.flight_recorder().events() if e.kind == "fleet"
    ]
    assert len(fleet_events) - fleet_events0 == sum(
        final[d] - base[d] for d in final
    )
    kinds = {e.meta["decision"] for e in fleet_events}
    assert kinds >= {
        "router_weights",
        "group_cap",
        "restore_cap",
        "spawn",
        "retire",
    }


def test_fleet_controller_admission_kwargs_bridge():
    """FleetControlConfig.admission_kwargs() splats cleanly into
    AdmissionConfig — the serve wiring cannot drift fields."""
    cfg = FleetControlConfig(
        slo_classes={"gold": 1.0},
        default_slo_class="gold",
        tenant_weights={"a": 2.0},
    )
    ac = AdmissionConfig(**cfg.admission_kwargs())
    assert ac.slo_classes == {"gold": 1.0}
    assert ac.default_slo_class == "gold"
    assert ac.tenant_fair_share is True
    assert ac.tenant_weight("a") == 2.0
    assert ac.tenant_weight("b") == 1.0


# ---------------------------------------------------------------------------
# Elastic lifecycle on a REAL fleet + draining /readyz (satellite 2)
# ---------------------------------------------------------------------------


def test_elastic_spawn_retire_zero_lost_byte_identical(params):
    """One full elastic cycle against live traffic: a spawned replica
    joins routing (same shared config, so the construction-time audit
    holds), retire drains it through the shared host tier with ZERO
    lost requests, and every response is byte-identical to the fixed
    fleet's greedy baseline. Scale counters move in lockstep across
    Prometheus and stats()."""
    fam = REGISTRY.get("gateway_fleet_scale_total")

    def scale_values():
        return {
            a: fam.labels(action=a).value
            for a in ("spawn", "drain", "retire")
        }

    fleet = _fleet(params)
    try:
        prompts = [_HEADER + f"elastic {i}" for i in range(4)]
        base_scale = scale_values()
        baseline = [
            fleet.submit(p, max_new_tokens=4, temperature=0.0, seed=7)
            .result(timeout=300)
            .text
            for p in prompts
        ]
        idx = fleet.spawn_replica()
        assert idx == 2
        assert fleet.states[idx] == "serving"
        assert idx in fleet.router.healthy()
        assert fleet.stats()["serving_replicas"] == 3
        # Traffic in flight across 3 replicas while the spawned one
        # retires: the drain finishes whatever landed on it first.
        futs = [
            fleet.submit(p, max_new_tokens=4, temperature=0.0, seed=7)
            for p in prompts
        ]
        out = fleet.retire_replica(idx, wait_s=120.0)
        assert out["replica"] == idx and out["serving"] == 2
        texts = [f.result(timeout=300).text for f in futs]
        assert texts == baseline  # zero lost, byte-identical
        assert fleet.states[idx] == "retired"
        assert idx not in fleet.router.healthy()
        hb = fleet.heartbeat()
        assert hb["alive"] is True  # retired loop must not read dead
        assert hb["replicas"][idx]["state"] == "retired"
        s = fleet.stats()
        assert s["serving_replicas"] == 2
        assert s["scale_events"] == {"spawn": 1, "drain": 1, "retire": 1}
        now_scale = scale_values()
        for action, n in s["scale_events"].items():
            assert now_scale[action] - base_scale[action] == n, action
        # The retired slot is never reused and never routed.
        ids = fleet.batchers[0].tokenizer.encode(_HEADER + "post-retire")
        for _ in range(4):
            assert fleet.router.route(ids)[0] != idx
        # Flight ring witnessed the lifecycle.
        scale_evs = [
            e
            for e in _flight.flight_recorder().events()
            if e.kind == "scale" and e.meta.get("replica") == idx
        ]
        assert [e.meta["action"] for e in scale_evs] == [
            "spawn",
            "drain",
            "retire",
        ]
    finally:
        fleet.close()


def test_draining_replica_distinct_in_readyz_and_router_skips(params):
    """Satellite 2: a DRAINING replica reports its own state — /readyz
    stays ready and names it under ``draining_replicas`` (not
    ``wedged_replicas``), the router skips it for NEW work, and its
    in-flight work still completes."""
    from llm_consensus_tpu.server.gateway import Gateway, GatewayConfig

    fleet = _fleet(params)
    try:
        gw = Gateway(
            FleetBackend(fleet),
            config=GatewayConfig(port=0, ready_stall_s=30.0),
            registry=MetricsRegistry(),
        )
        ready, doc = gw._readiness()
        assert ready is True
        assert doc["backend"]["replicas"][1]["state"] == "serving"
        fleet.states[1] = "draining"
        try:
            ready, doc = gw._readiness()
            assert ready is True, doc  # draining is NOT wedged
            assert doc["draining_replicas"] == [1]
            assert "wedged_replicas" not in doc
            assert doc["backend"]["replicas"][1]["state"] == "draining"
            # New work skips the draining replica...
            assert fleet.router.healthy() == [0]
            ids = fleet.batchers[0].tokenizer.encode(
                _HEADER + "fresh work"
            )
            for _ in range(3):
                assert fleet.router.route(ids)[0] == 0
            # ...while work already on it still completes.
            fut = fleet.batchers[1].submit(
                _HEADER + "in-flight finishes", max_new_tokens=4
            )
            assert isinstance(fut.result(timeout=300).text, str)
        finally:
            fleet.states[1] = "serving"
        ready, doc = gw._readiness()
        assert ready is True and "draining_replicas" not in doc
        assert 1 in fleet.router.healthy()
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# Router weight steering shifts load (stub batchers: pure routing)
# ---------------------------------------------------------------------------


def test_router_weights_steer_load_balance():
    """set_weights biases the router's least-load pick: with equal raw
    load, new work flows to the replica whose weight is lower (the
    controller inflates a hot replica's cost so it repels work)."""
    from llm_consensus_tpu.serving.fleet import PrefixRouter

    class _Stub:
        def prefix_probe(self, ids):
            return {"registry_tokens": 0, "host_tokens": 0}

        def load_cost(self):
            return 100.0

        def heartbeat(self):
            return {"alive": True, "last_tick_age_s": 0.0}

    stubs = [_Stub(), _Stub()]
    router = PrefixRouter(stubs, FleetConfig(replicas=2), page_size=16)
    router.set_weights([4.0, 1.0])
    assert router.weights() == [4.0, 1.0]
    idx, _ = router.route([1, 2, 3])
    assert idx == 1  # 100*1.0 < 100*4.0
    router.set_weights([1.0, 4.0])
    idx, _ = router.route([1, 2, 3])
    assert idx == 0
