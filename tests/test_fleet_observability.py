"""Fleet-wide observability federation (PR 20).

Covers the three coupled mechanisms end to end: cross-hop trace joins
(an ``X-Trace-Id`` rides the forwarded REQUEST and the peer ADOPTS it,
so the front's routing spans and the peer's serving spans share one
id; the remote-store wire carries the same id so store ops land as
spans/flight events on the owning trace), fleet aggregation
(``/metrics?fleet=1`` merges per-host expositions under ``host=``
labels with sums in lockstep; ``/debug/flight?fleet=1`` merges flight
rings onto one timebase via the RTT-halving clock-offset estimator,
one Chrome ``pid`` pair per host), and per-hop attribution (response
``meta["hops"]`` partitions the request's latency into front_route /
admission_wait / prefill / decode / handoff / wire_transfer, mirrored
into ``gateway_hop_seconds{hop=}``, with ``gateway_slo_burn_rate``
readable by the PR-19 FleetController as spawn pressure).

CPU-only: real sockets between in-process gateways (which share the
process-global trace store and flight ring — assertions here are
about joins and presence, never exact per-host event counts).
"""

import asyncio
import json
import re
import time

import numpy as np
import pytest

from llm_consensus_tpu.backends.fake import FakeBackend
from llm_consensus_tpu.server.admission import (
    AdmissionConfig,
    AdmissionController,
)
from llm_consensus_tpu.server.client import GatewayClient
from llm_consensus_tpu.server.gateway import (
    Gateway,
    GatewayConfig,
    GatewayThread,
    _merge_metrics_text,
)
from llm_consensus_tpu.server.metrics import MetricsRegistry
from llm_consensus_tpu.utils import tracing

_TID = "feedfacefeedface"


def _boot(backend, admission=None, **gw_kw):
    """Gateway on an ephemeral port with an isolated registry."""
    reg = MetricsRegistry()
    gw = Gateway(
        backend,
        config=GatewayConfig(
            port=0, admission=admission or AdmissionConfig(), **gw_kw
        ),
        registry=reg,
    )
    handle = GatewayThread(gw).start()
    return handle, GatewayClient("127.0.0.1", handle.port), reg


def _flatten(nodes):
    for n in nodes:
        yield n
        yield from _flatten(n["children"])


# ---------------------------------------------------------------------------
# Trace adoption: X-Trace-Id on the request roots the local spans
# ---------------------------------------------------------------------------


def test_incoming_trace_id_is_adopted():
    handle, client, _ = _boot(FakeBackend())
    try:
        r = client.generate("adopt me", headers={"X-Trace-Id": _TID})
        assert r["trace_id"] == _TID
        tree = client.traces(_TID)
        assert tree["meta"]["adopted"] is True
        names = {n["name"] for n in _flatten(tree["spans"])}
        assert "queued" in names and "execute" in names
        # A hostile/corrupt id is NOT adopted — a fresh id is minted.
        r = client.generate("bad id", headers={"X-Trace-Id": "zz!"})
        assert r["trace_id"] != "zz!" and r["trace_id"]
    finally:
        handle.drain()
    # fleet_obs=False: the header is ignored entirely.
    handle, client, _ = _boot(FakeBackend(), fleet_obs=False)
    try:
        r = client.generate("no obs", headers={"X-Trace-Id": _TID})
        assert r["trace_id"] != _TID
        assert "hops" not in (r.get("meta") or {})
    finally:
        handle.drain()


def test_trace_joins_across_real_peer_forward():
    """ISSUE 20 acceptance (join half): a request forwarded through a
    front gateway carries ONE trace id end to end — the front mints
    it, the peer adopts it, the relayed response and header agree, and
    the relayed ``meta["hops"]`` gains the front's own routing hop."""
    peer_h, _, peer_reg = _boot(FakeBackend())
    peer_url = f"http://127.0.0.1:{peer_h.port}"
    front_h, front_client, front_reg = _boot(
        FakeBackend(), peers=(peer_url,)
    )
    try:
        resp, data = front_client._request(
            "POST", "/v1/generate", {"prompt": "join me"}
        )
        assert resp.getheader("X-Peer") == peer_url
        tid = resp.getheader("X-Trace-Id")
        assert tid
        doc = json.loads(data)
        # The PEER served it, under the FRONT's id (adoption).
        assert doc["trace_id"] == tid
        hops = doc["meta"]["hops"]
        assert hops["front_route"] >= 0.0
        assert hops["decode"] >= 0.0 and hops["admission_wait"] >= 0.0
        # The adopted trace is retrievable under the shared id with the
        # peer's serving spans in it.
        tree = front_client.traces(tid)
        assert tree["meta"]["adopted"] is True
        assert "queued" in {n["name"] for n in _flatten(tree["spans"])}
        # Both tiers observed their hop histograms.
        assert 'gateway_hop_seconds_bucket{hop="front_route"' in (
            front_reg.render()
        )
        assert 'gateway_hop_seconds_bucket{hop="decode"' in (
            peer_reg.render()
        )
    finally:
        front_h.drain()
        peer_h.drain()


# ---------------------------------------------------------------------------
# Clock-offset estimator + fleet timeline merge
# ---------------------------------------------------------------------------


def test_clock_offset_midpoint_estimator():
    off, rtt = Gateway._clock_offset({"now_pc": 50.0}, 100.0, 100.2)
    assert off == pytest.approx(50.1)
    assert rtt == pytest.approx(0.2)
    # Peers predating the stamp yield no estimate.
    assert Gateway._clock_offset({}, 0.0, 1.0) == (None, None)
    assert Gateway._clock_offset({"now_pc": "x"}, 0.0, 1.0) == (None, None)
    # Min-RTT observation wins (NTP-style): a tighter exchange
    # replaces a looser one; a looser one never degrades the estimate.
    gw = Gateway(FakeBackend(), config=GatewayConfig(port=0))
    gw._note_offset("h", 1.0, 0.5)
    gw._note_offset("h", 9.0, 0.8)
    assert gw._peer_offsets["h"] == (1.0, 0.5)
    gw._note_offset("h", 3.0, 0.1)
    assert gw._peer_offsets["h"] == (3.0, 0.1)
    gw._note_offset("h", None, None)  # no stamp -> no-op
    assert gw._peer_offsets["h"] == (3.0, 0.1)


def test_merge_fleet_corrects_skew_monotonic():
    """Synthetic skewed rings: host B's perf_counter origin is ~990 s
    ahead; after its probe-derived offset is applied the merged
    timeline interleaves the hosts in true wall order, monotonically."""
    from llm_consensus_tpu.serving.flight import FlightEvent, merge_fleet

    a = [
        FlightEvent(seq=i, kind="program", t0=10.0 + 0.2 * i, dur=0.01,
                    trace_id=_TID, meta={})
        for i in range(3)
    ]
    b = [
        FlightEvent(seq=i, kind="store_op", t0=1000.05 + 0.2 * i,
                    dur=0.01, trace_id=_TID, meta={})
        for i in range(3)
    ]
    merged = merge_fleet({"A": (a, 0.0), "B": (b, -990.0)})
    t0s = [e.t0 for e in merged]
    assert t0s == sorted(t0s)
    assert [e.meta["host"] for e in merged] == [
        "A", "B", "A", "B", "A", "B"
    ]
    assert all(e.trace_id == _TID for e in merged)
    # Inputs untouched (new events, corrected copies).
    assert "host" not in a[0].meta and b[0].t0 == pytest.approx(1000.05)


def test_to_chrome_fleet_one_pid_pair_per_host():
    from llm_consensus_tpu.serving.flight import (
        FlightEvent,
        to_chrome_fleet,
    )

    a = [FlightEvent(seq=0, kind="program", t0=5.0, dur=0.01,
                     trace_id=None, meta={"rows": 2})]
    b = [FlightEvent(seq=0, kind="handoff", t0=1000.0, dur=0.02,
                     trace_id=_TID, meta={})]
    doc = to_chrome_fleet({"A": (a, 0.0), "B": (b, -994.0)})
    names = {
        ev["args"]["name"]: ev["pid"]
        for ev in doc["traceEvents"]
        if ev.get("name") == "process_name"
    }
    assert "A serving" in names and "B serving" in names
    assert len({names["A serving"], names["B serving"]}) == 2
    slices = [
        ev for ev in doc["traceEvents"] if ev.get("ph") == "X"
    ]
    # One global base over the CORRECTED stamps: every ts >= 0 and the
    # hosts' slices land ~1 s apart, not ~995 s.
    assert slices and all(ev["ts"] >= 0 for ev in slices)
    assert max(ev["ts"] for ev in slices) < 5e6


# ---------------------------------------------------------------------------
# /metrics federation: host= labels, sums in lockstep
# ---------------------------------------------------------------------------


def test_merge_metrics_text_sums_lockstep():
    ra, rb = MetricsRegistry(), MetricsRegistry()
    ra.counter("demo_total", "demo").labels(route="/x").inc(3)
    rb.counter("demo_total", "demo").labels(route="/x").inc(4)
    ha = ra.histogram("lat_seconds", "lat", buckets=(1.0,))
    ha.observe(0.5)
    merged = _merge_metrics_text(
        {"self": ra.render(), "http://p:1": rb.render()}
    )
    assert 'demo_total{host="self",route="/x"} 3' in merged
    assert 'demo_total{host="http://p:1",route="/x"} 4' in merged
    # HELP/TYPE dedupe to ONE copy per family.
    assert merged.count("# TYPE demo_total counter") == 1
    # Histogram series group under their base family with the host
    # label injected first.
    assert 'lat_seconds_bucket{host="self",le="1"} 1' in merged
    assert 'lat_seconds_count{host="self"} 1' in merged
    # Values relay verbatim: the merged sum IS the per-host sum.
    vals = [
        float(m.group(1))
        for m in re.finditer(r"^demo_total\{[^}]*\} (\S+)$",
                             merged, re.M)
    ]
    assert sum(vals) == 7.0


def test_metrics_fleet_federation_live():
    peer_h, peer_client, _ = _boot(FakeBackend())
    peer_url = f"http://127.0.0.1:{peer_h.port}"
    front_h, front_client, _ = _boot(FakeBackend(), peers=(peer_url,))
    try:
        front_client.generate("federate me")
        _, data = front_client._request("GET", "/metrics?fleet=1")
        merged = data.decode()
        assert 'host="self"' in merged
        assert f'host="{peer_url}"' in merged
        # The forwarded generate was counted once per tier; the merged
        # view's sum over the family equals the per-tier scrapes' sum.
        pat = re.compile(
            r'^gateway_requests_total\{[^}]*route="/v1/generate"[^}]*\}'
            r" (\S+)$",
            re.M,
        )
        merged_sum = sum(float(v) for v in pat.findall(merged))
        plain_sum = sum(
            float(v)
            for text in (front_client.metrics(), peer_client.metrics())
            for v in pat.findall(text)
        )
        assert merged_sum == plain_sum == 2.0
    finally:
        front_h.drain()
        peer_h.drain()
    # fleet_obs=False: ?fleet=1 degrades to the plain exposition.
    handle, client, _ = _boot(FakeBackend(), fleet_obs=False)
    try:
        _, data = client._request("GET", "/metrics?fleet=1")
        assert 'host="' not in data.decode()
    finally:
        handle.drain()


# ---------------------------------------------------------------------------
# /debug/flight?fleet=1: merged cross-process timeline (live)
# ---------------------------------------------------------------------------


def test_flight_fleet_merged_timeline_live():
    from llm_consensus_tpu.serving import flight as _flight

    was = _flight.enabled()
    _flight.set_enabled(True)
    peer_h, _, _ = _boot(FakeBackend())
    peer_url = f"http://127.0.0.1:{peer_h.port}"
    front_h, front_client, _ = _boot(FakeBackend(), peers=(peer_url,))
    try:
        _flight.flight_recorder().record(
            "store_op",
            time.perf_counter(),
            0.001,
            trace_id=_TID,
            op="put_many",
        )
        doc = front_client._json("GET", "/debug/flight?fleet=1")
        assert doc["unreachable"] == []
        assert doc["hosts"]["self"]["offset_s"] == 0.0
        # The scrape itself doubled as a clock probe: the peer has a
        # finite offset/rtt estimate (near zero — same process clock).
        assert abs(doc["hosts"][peer_url]["offset_s"]) < 5.0
        assert doc["hosts"][peer_url]["rtt_s"] > 0.0
        hosts_seen = {e["host"] for e in doc["events"]}
        assert {"self", peer_url} <= hosts_seen
        # The tagged store op joins the merged view by its trace id.
        assert any(
            e.get("trace_id") == _TID for e in doc["events"]
        )
        chrome = front_client._json(
            "GET", "/debug/flight?fleet=1&format=chrome"
        )
        pnames = {
            ev["args"]["name"]
            for ev in chrome["traceEvents"]
            if ev.get("name") == "process_name"
        }
        assert "self serving" in pnames
        assert f"{peer_url} serving" in pnames
    finally:
        _flight.set_enabled(was)
        front_h.drain()
        peer_h.drain()


# ---------------------------------------------------------------------------
# Remote-store wire: ops land as spans/flight events on the owning trace
# ---------------------------------------------------------------------------


def test_store_ops_ride_the_owning_trace():
    from llm_consensus_tpu.serving import flight as _flight
    from llm_consensus_tpu.serving.offload import HostPageStore
    from llm_consensus_tpu.serving.remote_store import (
        PageStoreServer,
        RemotePageStore,
    )

    was = _flight.enabled()
    _flight.set_enabled(True)
    store = HostPageStore(budget_bytes=64 << 20)
    server = PageStoreServer(store).start()
    client = RemotePageStore(server.endpoint, timeout_s=10.0)
    trace = tracing.trace_store().start("store-owner")
    planes = (
        np.arange(64, dtype=np.float32),
        np.ones(32, dtype=np.float32),
    )
    try:
        with tracing.use_trace(trace):
            client.put_counted(("chain", 0), planes)
            got = client.get(("chain", 0))
        assert got is not None
        ops = {
            s.meta["op"]: s
            for s in trace.spans()
            if s.name == "store_op"
        }
        assert {"put_counted", "get"} <= set(ops)
        assert ops["put_counted"].meta["tx_bytes"] > 0
        assert ops["get"].meta["rx_bytes"] > 0
        assert all(s.duration > 0 for s in ops.values())
        # The id crossed the WIRE: both the client-side and the
        # server-side flight events carry the owning trace's id.
        tagged = [
            e
            for e in _flight.flight_recorder().events()
            if e.kind == "store_op" and e.trace_id == trace.trace_id
        ]
        assert len(tagged) >= 2
        assert {e.meta.get("op") for e in tagged} >= {
            "put_counted",
            "get",
        }
        # Control ops outside a trace context do not tag events...
        n_before = len(_flight.flight_recorder().events())
        assert ("chain", 0) in client
        assert len(client) >= 1  # stats piggyback (control plane)
        n_after = len(
            [
                e
                for e in _flight.flight_recorder().events()
                if e.kind == "store_op"
            ]
        )
        assert n_after <= n_before  # no store_op churn from control ops
        # ...and every stamped reply refined the clock estimate.
        assert client.clock_offset is not None
        assert client.clock_rtt > 0.0
    finally:
        _flight.set_enabled(was)
        client.close()
        server.close()


# ---------------------------------------------------------------------------
# Per-hop attribution: hops ~= e2e; burn-rate readable by the controller
# ---------------------------------------------------------------------------


def test_hop_breakdown_tracks_client_e2e():
    """ISSUE 20 acceptance (attribution half): the response's hop sum
    lands within tolerance of the client-observed latency when the
    backend dominates (FakeBackend sleeping 150 ms)."""
    handle, client, reg = _boot(FakeBackend(latency=0.15))
    try:
        t0 = time.monotonic()
        r = client.generate("how long")
        e2e = time.monotonic() - t0
        hops = r["meta"]["hops"]
        assert set(hops) <= {
            "front_route",
            "admission_wait",
            "prefill",
            "decode",
            "handoff",
            "wire_transfer",
        }
        assert hops["decode"] >= 0.14
        total = sum(hops.values())
        assert abs(total - e2e) <= 0.10 * e2e + 0.05
        text = reg.render()
        assert 'gateway_hop_seconds_bucket{hop="decode"' in text
        assert 'gateway_hop_seconds_bucket{hop="admission_wait"' in text
    finally:
        handle.drain()


def test_burn_rate_gauge_and_fleet_controller_lockstep():
    from llm_consensus_tpu.serving.fleet_control import (
        FleetControlConfig,
        FleetController,
    )

    async def main():
        reg = MetricsRegistry()
        c = AdmissionController(
            AdmissionConfig(slo_classes={"fast": 0.05}), registry=reg
        )
        c._burn_observe("fast", missed=True)
        c._burn_observe("fast", missed=True)
        c._burn_observe("fast", missed=False)
        rates = c.burn_rates()
        assert 0.0 < rates["fast"] < 1.0
        assert c.stats()["slo_burn_rate"] == rates
        m = re.search(
            r'gateway_slo_burn_rate\{class="fast"\} (\S+)', reg.render()
        )
        assert m and float(m.group(1)) == pytest.approx(
            rates["fast"], rel=1e-9
        )
        # The PR-19 controller reads the same numbers in-process once
        # the CLI attaches the gateway's admission tier.
        fc = FleetController(
            type("_Fleet", (), {})(), FleetControlConfig()
        )
        assert fc.burn_rates() == {}
        fc.attach_admission(c)
        assert fc.burn_rates() == rates
        assert fc.stats()["fleet_burn_rate"] == rates

    asyncio.run(main())
