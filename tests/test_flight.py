"""Serving flight recorder + roofline attribution (PR 10).

Contract layers:

- RING: the bounded evict-oldest FlightRecorder counts every eviction
  and mirrors it into ``gateway_flight_dropped_total`` (lockstep), and
  records nothing when disabled.
- CHROME EXPORT: ``to_chrome`` emits valid Chrome trace-event JSON
  (every event carries ts/ph/pid/tid, the whole doc JSON round-trips),
  and the device track reconstructs EXACTLY the programs
  ``gateway_device_programs_total`` counted over the same window — the
  PR's acceptance criterion.
- TOKEN TIMELINE: ``gateway_tbt_seconds`` moves in lockstep with the
  batcher's ``stats()`` mirror and with the per-request summaries'
  gap counts; TTFT moves once per request on both its surfaces.
- COST MODEL: modeled KV tokens are invariant to HOW the work was
  packaged into programs — fused-vs-split totals are identical over
  the same burst, and (at a round-aligned token budget) spec-on/off
  target KV writes are identical — while the weight term counts
  programs (the thing fusion/speculation amortize).
- GATEWAY: ``/debug/flight`` (+ ``?format=chrome``), ``/debug/requests``
  (+ ``?id=`` by request OR trace id), response ``meta``, and the shed
  event on a 429.
- CI: the ``bench.py --serve-flight-overhead`` dual tok/s gate and the
  ``scripts/bench_history.py`` no-data rule (CHIP UNREACHABLE is never
  a 0-tok/s measurement).
"""

import json
import re
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import (
    init_params,
    model_param_bytes,
    program_hbm_cost,
)
from llm_consensus_tpu.serving import flight
from llm_consensus_tpu.serving.continuous import (
    ContinuousBackend,
    ContinuousBatcher,
    ContinuousConfig,
)
from llm_consensus_tpu.server.metrics import (
    FLIGHT_DROPPED,
    REGISTRY,
    TBT_SECONDS,
    MetricsRegistry,
)

ROOT = Path(__file__).resolve().parent.parent

CFG = get_config("test-tiny")

_HEADER = "Panel shared header for every persona, forty ch: "
_CCFG = dict(
    max_slots=4,
    page_size=16,
    n_pages=96,
    pages_per_seq=10,
    max_new_tokens=8,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _serve(batcher, prompts, **kw):
    futs = [batcher.submit(p, **kw) for p in prompts]
    return [f.result(timeout=180) for f in futs]


def _quiesce(batcher, timeout=20.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        s = batcher.stats()
        if (
            s["active_slots"] == 0
            and s["prefilling_slots"] == 0
            and s["dispatch_inflight"] == 0
            and s["waiting"] == 0
        ):
            return s
        time.sleep(0.01)
    raise AssertionError(f"batcher did not quiesce: {batcher.stats()}")


def _programs_total() -> float:
    return sum(
        v
        for k, v in REGISTRY.snapshot().items()
        if k.startswith("gateway_device_programs_total")
    )


# ---------------------------------------------------------------------------
# Ring: overflow + drop-counter lockstep, disable, request log
# ---------------------------------------------------------------------------


def test_flight_ring_overflow_drop_counter_lockstep():
    rec = flight.FlightRecorder(capacity=8)
    before = FLIGHT_DROPPED.value
    for i in range(20):
        rec.record("unit", float(i), i=i)
    assert len(rec) == 8
    assert rec.dropped == 12
    # The Prometheus mirror moved by exactly the ring's own count.
    assert FLIGHT_DROPPED.value - before == 12
    # Evict-oldest: the survivors are the newest 8.
    assert [e.meta["i"] for e in rec.events()] == list(range(12, 20))
    # Shrinking the cap sheds immediately, still counted.
    rec.configure(capacity=3)
    assert len(rec) == 3 and rec.dropped == 17
    assert FLIGHT_DROPPED.value - before == 17


def test_flight_disabled_records_nothing():
    rec = flight.FlightRecorder(capacity=8)
    flight.set_enabled(False)
    try:
        assert rec.record("unit", 0.0) is None
        assert len(rec) == 0 and rec.dropped == 0
    finally:
        flight.set_enabled(True)
    assert rec.record("unit", 0.0) is not None


def test_request_log_bounds_and_trace_lookup():
    log = flight.RequestLog(max_requests=2)
    log.add({"id": "req-a", "trace_id": "t-a"})
    log.add({"id": "req-b", "trace_id": "t-b"})
    log.add({"id": "req-c", "trace_id": None})
    assert len(log) == 2
    assert log.get("req-a") is None  # evicted (oldest)
    assert log.get("t-a") is None
    assert log.get("req-b")["id"] == "req-b"
    assert log.get("t-b")["id"] == "req-b"  # trace-id lookup
    assert [d["id"] for d in log.recent(10)] == ["req-c", "req-b"]


def test_request_log_shared_trace_returns_every_member():
    """One trace can cover several generations (a consensus panel
    fan-out submits every member under the request's trace): the trace
    key reaches ALL of them, newest first, surviving partial
    eviction."""
    log = flight.RequestLog(max_requests=3)
    log.add({"id": "req-1", "trace_id": "t-panel"})
    log.add({"id": "req-2", "trace_id": "t-panel"})
    log.add({"id": "req-3", "trace_id": "t-panel"})
    assert [d["id"] for d in log.get_all("t-panel")] == [
        "req-3", "req-2", "req-1",
    ]
    assert log.get("t-panel")["id"] == "req-3"  # latest wins
    assert log.get_all("req-2") == [{"id": "req-2", "trace_id": "t-panel"}]
    # Evicting one member prunes only its index entry.
    log.add({"id": "req-4", "trace_id": None})
    assert [d["id"] for d in log.get_all("t-panel")] == ["req-3", "req-2"]


def test_percentile_nearest_rank():
    assert flight.percentile([], 99) == 0.0
    vals = [5.0, 1.0, 3.0, 2.0, 4.0]
    assert flight.percentile(vals, 50) == 3.0
    assert flight.percentile(vals, 99) == 5.0
    # Nearest-rank: every answer is an actually-observed value.
    assert flight.percentile(vals, 1) == 1.0


# ---------------------------------------------------------------------------
# Chrome export: schema validity on synthetic events
# ---------------------------------------------------------------------------


def test_chrome_export_schema_and_tracks():
    evs = [
        flight.FlightEvent(0, "program", 10.0, 0.5, None, {"kind": "decode"}),
        flight.FlightEvent(1, "program", 10.6, 0.0, None, {"kind": "draft"}),
        flight.FlightEvent(2, "host", 9.5, 0.4, None, {}),
        flight.FlightEvent(3, "admit", 9.4, 0.0, "tr1", {"id": "req-9"}),
        flight.FlightEvent(4, "restore", 9.6, 0.1, "tr1", {"page": 7}),
        flight.FlightEvent(
            5, "request", 9.3, 1.9, "tr1", {"id": "req-9", "tokens": 8}
        ),
    ]
    doc = flight.to_chrome(evs)
    # The whole export must survive a JSON round trip (what the
    # gateway serves and Perfetto loads).
    doc = json.loads(json.dumps(doc))
    events = doc["traceEvents"]
    assert all(
        {"ts", "ph", "pid", "tid"} <= set(e) for e in events
    ), "every Chrome event needs ts/ph/pid/tid"
    # ts is relative to the earliest event — never negative.
    assert all(e["ts"] >= 0 for e in events)
    dev = [
        e
        for e in events
        if e.get("cat") == "device" and e["ph"] == "X"
    ]
    assert [e["name"] for e in dev] == ["decode", "draft"]
    assert dev[1]["dur"] == 0  # in-flight/annotation programs allowed
    host = [e for e in events if e.get("cat") == "host"]
    assert len(host) == 1 and host[0]["ph"] == "X"
    # Durationless scheduler events render as instants, timed ones as
    # slices; both carry the trace id in args.
    admit = next(e for e in events if e.get("name") == "admit")
    assert admit["ph"] == "i" and admit["args"]["trace_id"] == "tr1"
    restore = next(e for e in events if e.get("name") == "restore")
    assert restore["ph"] == "X" and restore["dur"] > 0
    # The request span sits on its own named thread row.
    req = next(e for e in events if e.get("cat") == "request")
    names = [
        e
        for e in events
        if e["ph"] == "M"
        and e["name"] == "thread_name"
        and e["pid"] == req["pid"]
        and e["tid"] == req["tid"]
    ]
    assert names and names[0]["args"]["name"] == "req-9"


# ---------------------------------------------------------------------------
# Acceptance: the device track reconstructs gateway_device_programs_total
# ---------------------------------------------------------------------------


def test_device_track_reconstructs_program_counter(params):
    b = ContinuousBatcher(
        CFG, params, config=ContinuousConfig(**_CCFG)
    )
    try:
        # Warm so the measured window is steady-state-ish (irrelevant
        # to counts, keeps the test fast on recompiles).
        _serve(b, [_HEADER + "warm"], max_new_tokens=4)
        _quiesce(b)
        flight.flight_recorder().clear()
        before = _programs_total()
        prompts = [_HEADER + f"tail {i}" for i in range(4)] + [
            "unrelated prompt entirely"
        ]
        outs = _serve(b, prompts, max_new_tokens=8)
        _quiesce(b)
        delta = _programs_total() - before
    finally:
        b.close()
    assert all(o.num_tokens >= 1 for o in outs)
    evs = flight.flight_recorder().events()
    prog_evs = [e for e in evs if e.kind == "program"]
    assert len(prog_evs) == delta > 0
    doc = json.loads(json.dumps(flight.to_chrome(evs)))
    events = doc["traceEvents"]
    assert all({"ts", "ph", "pid", "tid"} <= set(e) for e in events)
    dev = [
        e for e in events if e.get("cat") == "device" and e["ph"] == "X"
    ]
    # THE acceptance assertion: the Chrome device track holds exactly
    # the programs the counter counted over the burst window.
    assert len(dev) == delta
    # Fetched programs carry real windows; the quiesced burst has no
    # pending ones, so every decode/fused program has a duration.
    timed = [e for e in dev if e["name"] in ("decode", "fused")]
    assert timed and all(e["dur"] > 0 for e in timed)
    # The burst's journey shows up as typed scheduler events + one
    # track slice per request.
    kinds = {e.kind for e in evs}
    assert {"admit", "request", "program"} <= kinds
    req_slices = [e for e in events if e.get("cat") == "request"]
    assert len(req_slices) == len(prompts)


# ---------------------------------------------------------------------------
# Token timeline: TTFT/TBT Prometheus <-> stats <-> summaries lockstep
# ---------------------------------------------------------------------------


def test_ttft_tbt_lockstep_and_summaries(params):
    b = ContinuousBatcher(CFG, params, config=ContinuousConfig(**_CCFG))
    try:
        st0 = b.stats()
        h0 = (TBT_SECONDS.count, TBT_SECONDS.sum)
        outs = _serve(
            b, [_HEADER + f"t{i}" for i in range(3)], max_new_tokens=8
        )
        _quiesce(b)
        st1 = b.stats()
    finally:
        b.close()
    # stats() moved by exactly what the process-wide histogram moved
    # (this batcher is the only serving activity in the window).
    d_count = st1["tbt_seconds_count"] - st0["tbt_seconds_count"]
    assert TBT_SECONDS.count - h0[0] == d_count
    assert TBT_SECONDS.sum - h0[1] == pytest.approx(
        st1["tbt_seconds_sum"] - st0["tbt_seconds_sum"]
    )
    # One TBT observation per generated token past each request's
    # first — and the per-request summaries carry the same counts.
    assert d_count == sum(o.num_tokens - 1 for o in outs)
    assert d_count == sum(o.timing["tbt_count"] for o in outs)
    # TTFT: once per request, on both its batcher surfaces.
    assert st1["ttft_seconds_count"] - st0["ttft_seconds_count"] == len(outs)
    for o in outs:
        t = o.timing
        assert t["new_tokens"] == o.num_tokens
        assert t["ttft_s"] > 0 and t["duration_s"] >= t["ttft_s"]
        assert 0 <= t["tbt_p50_s"] <= t["tbt_p99_s"] <= t["tbt_max_s"]
        # The summary is retrievable from the process RequestLog by
        # request id (trace-id lookup is exercised via the gateway).
        assert flight.request_log().get(t["id"]) == t


# ---------------------------------------------------------------------------
# Cost model: packaging-invariant KV, program-counting weights
# ---------------------------------------------------------------------------


def test_program_hbm_cost_units():
    w_bytes, w_params = model_param_bytes(
        {"a": jnp.zeros((4, 8), jnp.float32), "b": jnp.zeros((3,), jnp.int8)}
    )
    assert w_bytes == 4 * 8 * 4 + 3
    assert w_params == 35
    c = program_hbm_cost(
        CFG,
        weight_bytes=1000,
        weight_params=10,
        kv_token_bytes=7,
        kv_read_tokens=20,
        kv_write_tokens=5,
        tokens=3,
    )
    assert c["hbm_bytes"] == 1000 + 25 * 7
    assert c["flops"] == 2 * 10 * 3 + 4 * CFG.n_heads * CFG.head_dim * 20
    assert c["kv_read_tokens"] == 20 and c["kv_write_tokens"] == 5


def _mbu_totals(stats, keys=("kv_read_tokens", "kv_write_tokens")):
    return {
        key: sum(
            stats[f"mbu_{key}_{kind}"]
            for kind in ("fused", "decode", "prefill", "spec")
        )
        for key in keys
    }


def test_cost_model_fused_vs_split_kv_parity(params):
    """The modeled KV traffic is a property of the WORK, not of how the
    scheduler packaged it into programs: the same burst served fused
    (chunks ride the decode dispatch) and split (standalone chunk
    programs) must model identical KV token totals — while the weight
    term moves with the program count, which is exactly what fusion
    saves. depth/sync pinned to 1 so retirement overshoot can't smear
    row-steps across the legs."""
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(
            **_CCFG, pipeline_depth=1, steps_per_sync=1
        ),
    )
    prompts = [_HEADER + f"tail {i}" for i in range(4)]

    def leg(ragged: bool):
        b.config.ragged_attention = ragged
        _quiesce(b)
        s0 = b.stats()
        texts = [r.text for r in _serve(b, prompts, max_new_tokens=8)]
        _quiesce(b)
        s1 = b.stats()
        d = {
            k: s1[k] - s0[k]
            for k in s1
            if k.startswith(("mbu_", "device_programs_"))
        }
        return texts, d

    try:
        _serve(b, [_HEADER + "warm fused"], max_new_tokens=4)  # compile
        b.config.ragged_attention = False
        _serve(b, [_HEADER + "warm split"], max_new_tokens=4)
        texts_on, d_on = leg(True)
        texts_off, d_off = leg(False)
    finally:
        b.close()
    assert texts_on == texts_off  # the PR-8 parity contract
    t_on, t_off = _mbu_totals(d_on), _mbu_totals(d_off)
    assert t_on["kv_read_tokens"] == t_off["kv_read_tokens"] > 0
    assert t_on["kv_write_tokens"] == t_off["kv_write_tokens"] > 0
    # Fusion ran fewer programs for the same work — fewer weight
    # streams, so the modeled bytes drop by (programs saved) * tree.
    progs_on = d_on["mbu_programs_fused"] + d_on["mbu_programs_decode"] + (
        d_on["mbu_programs_prefill"]
    )
    progs_off = d_off["mbu_programs_decode"] + d_off["mbu_programs_prefill"]
    assert d_on["mbu_programs_fused"] > 0 and d_off["mbu_programs_fused"] == 0
    assert progs_on < progs_off
    bytes_on = sum(
        d_on[f"mbu_hbm_bytes_{k}"] for k in ("fused", "decode", "prefill")
    )
    bytes_off = sum(
        d_off[f"mbu_hbm_bytes_{k}"] for k in ("fused", "decode", "prefill")
    )
    w_bytes, _ = model_param_bytes(params)
    assert bytes_off - bytes_on == (progs_off - progs_on) * w_bytes


def test_cost_model_spec_on_off_write_parity(params):
    """Target-pool KV writes are emitted-text-invariant across the
    speculation flip when the token budget is round-aligned: spec
    writes k+1 positions per round (rewinds are count bookkeeping, the
    traffic happened) and at self-draft acceptance 1.0 each round
    commits k+1 tokens, so a budget of 1 + m*(k+1) tokens makes the
    written totals exactly equal — the cost model must agree. Per
    verify round the target reads its pages ONCE for all k+1 queries
    (the ragged kernel's whole point), so spec READ totals come in
    BELOW the plain leg's k+1 separate programs.

    Prompts are UNIQUE from byte 0 and shorter than one page: no
    shared-prefix groups means no donor draft streams, whose catch-up
    fills can legitimately produce a short round for a staggered
    panel mate (measured: 3,3,1 emissions — correct, but not
    round-aligned), and no full-page registration means the second
    leg's prefill work matches the first's exactly."""
    k = 2
    new_tokens = 1 + 2 * (k + 1)  # first token from prefill + 2 rounds
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(
            **dict(_CCFG, max_new_tokens=new_tokens),
            pipeline_depth=1,
            steps_per_sync=1,
            spec_k=k,
        ),
        draft=(CFG, params),  # self-draft: the acceptance-1.0 ceiling
    )
    prompts = ["aaa question 1", "bbb question 2", "ccc question 3"]

    def leg(spec_on: bool):
        b.config.spec_decode = spec_on
        _quiesce(b)
        s0 = b.stats()
        outs = _serve(b, prompts, max_new_tokens=new_tokens)
        _quiesce(b)
        s1 = b.stats()
        d = {k_: s1[k_] - s0[k_] for k_ in s1 if k_.startswith("mbu_")}
        return outs, d

    try:
        for on in (True, False):  # compile both program families
            b.config.spec_decode = on
            _serve(b, [_HEADER + f"warm {on}"], max_new_tokens=new_tokens)
        outs_on, d_on = leg(True)
        outs_off, d_off = leg(False)
    finally:
        b.close()
    assert [o.text for o in outs_on] == [o.text for o in outs_off]
    # Round-aligned budget actually filled (no early EOS/stop): the
    # exact-parity precondition.
    assert all(o.num_tokens == new_tokens for o in outs_on)
    assert d_on["mbu_programs_spec"] > 0 and d_off["mbu_programs_spec"] == 0
    t_on, t_off = _mbu_totals(d_on), _mbu_totals(d_off)
    assert t_on["kv_write_tokens"] == t_off["kv_write_tokens"] > 0
    assert t_on["kv_read_tokens"] < t_off["kv_read_tokens"]
    # The per-request summaries carry the speculation tallies.
    for o in outs_on:
        t = o.timing
        assert t["spec_rounds"] == 2
        assert t["spec_accepted_per_round"] == pytest.approx(k)


def test_mbu_gauge_published_with_peak_configured(params):
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(**_CCFG, hbm_gbps=1.0),
    )
    try:
        st0 = b.stats()
        _serve(b, [_HEADER + "mbu probe"], max_new_tokens=8)
        _quiesce(b)
        st1 = b.stats()
    finally:
        b.close()
    assert st1["mbu_programs_decode"] - st0["mbu_programs_decode"] > 0
    assert st1["mbu_seconds_decode"] > st0["mbu_seconds_decode"]
    snap = REGISTRY.snapshot()
    mbu = {
        key: v for key, v in snap.items() if key.startswith("gateway_program_mbu")
    }
    assert 'gateway_program_mbu{kind="decode"}' in mbu
    assert all(v > 0 for v in mbu.values())


# ---------------------------------------------------------------------------
# Gateway: /debug/flight, /debug/requests, response meta, shed events
# ---------------------------------------------------------------------------


@pytest.fixture()
def gateway(params):
    from llm_consensus_tpu.server.gateway import (
        Gateway,
        GatewayConfig,
        GatewayThread,
    )

    b = ContinuousBatcher(CFG, params, config=ContinuousConfig(**_CCFG))
    gw = Gateway(
        ContinuousBackend(b),
        config=GatewayConfig(port=0),
        registry=MetricsRegistry(),
    )
    handle = GatewayThread(gw).start()
    yield gw, handle, b
    handle.drain()
    b.close()


def test_gateway_flight_and_requests_on_live_burst(gateway):
    from llm_consensus_tpu.server.client import GatewayClient

    gw, handle, b = gateway
    client = GatewayClient("127.0.0.1", handle.port, timeout=180)
    flight.flight_recorder().clear()
    n = 6
    results = [None] * n
    errs = []

    def one(i):
        try:
            results[i] = client.generate(
                _HEADER + f"gw {i}", max_new_tokens=6
            )
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errs and all(r is not None for r in results)
    _quiesce(b)
    # Response meta IS the /debug/requests summary, keyed both ways.
    r0 = results[0]
    assert r0["meta"]["trace_id"] == r0["trace_id"]
    by_rid = client.requests(r0["meta"]["id"])
    by_trace = client.requests(r0["trace_id"])
    assert by_rid == by_trace == r0["meta"]
    listing = client.requests()
    got = {d["id"] for d in listing["requests"]}
    assert {r["meta"]["id"] for r in results} <= got
    # The flight ring served over HTTP, plain and Chrome forms.
    fl = client.flight()
    assert fl["enabled"] is True and fl["n_events"] > 0
    kinds = {e["kind"] for e in fl["events"]}
    assert {"admit", "program", "request"} <= kinds
    doc = client.flight(format="chrome")
    assert all(
        {"ts", "ph", "pid", "tid"} <= set(e) for e in doc["traceEvents"]
    )
    assert sum(
        1 for e in doc["traceEvents"] if e.get("cat") == "request"
    ) >= n
    # Unknown id -> 404.
    from llm_consensus_tpu.server.client import GatewayHTTPError

    with pytest.raises(GatewayHTTPError) as ei:
        client.requests("req-nope")
    assert ei.value.status == 404
    # gateway_ttft_seconds (gateway surface) moved once per request —
    # the request-level lockstep with the batcher-side ttft mirror.
    snap = gw.registry.snapshot()
    assert snap["gateway_ttft_seconds_count"] == n


def test_gateway_shed_records_flight_event(params):
    from llm_consensus_tpu.backends.fake import FakeBackend
    from llm_consensus_tpu.server.admission import AdmissionConfig
    from llm_consensus_tpu.server.client import (
        GatewayClient,
        GatewayHTTPError,
    )
    from llm_consensus_tpu.server.gateway import (
        Gateway,
        GatewayConfig,
        GatewayThread,
    )

    gw = Gateway(
        FakeBackend(latency=0.5),
        config=GatewayConfig(
            port=0,
            admission=AdmissionConfig(max_queue=1, max_inflight=1),
        ),
        registry=MetricsRegistry(),
    )
    handle = GatewayThread(gw).start()
    client = GatewayClient("127.0.0.1", handle.port, timeout=60)
    flight.flight_recorder().clear()
    sheds = []

    def one(i):
        try:
            client.generate(f"burst {i}", max_new_tokens=4)
        except GatewayHTTPError as e:
            if e.status == 429:
                sheds.append(i)

    try:
        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    finally:
        handle.drain()
    assert sheds, "overload burst produced no 429s — resize the test"
    evs = [
        e for e in flight.flight_recorder().events() if e.kind == "shed"
    ]
    assert len(evs) == len(sheds)
    assert all(e.meta["route"] == "/v1/generate" for e in evs)


# ---------------------------------------------------------------------------
# CI: the bench A/B leg and the bench-history no-data rule
# ---------------------------------------------------------------------------


def test_bench_serve_flight_overhead_cpu_ab_leg(tmp_path):
    """PR-10 acceptance: --serve-flight-overhead passes its tok/s gate
    with the recorder on (PR-5 dual gate, loadavg-aware escalation),
    emits the machine-readable status field, and lands atomically."""
    out = tmp_path / "reports" / "flight_ab.json"
    r = subprocess.run(
        [
            sys.executable, "bench.py", "--tiny", "--cpu",
            "--serve-flight-overhead", "--serve-requests", "6",
            "--serve-slots", "2", "--new-tokens", "8",
            "--prompt-len", "64", "--serve-chunk", "1",
            "--serve-prefill-chunk", "64", "--out", str(out),
        ],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=570,
    )
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-2000:])
    payload = json.loads(out.read_text())
    assert payload == json.loads(r.stdout.strip().splitlines()[-1])
    assert payload["value"] > 0
    assert payload["status"] == "ok"  # the machine-readable satellite
    m = payload["metric"]
    assert "flight recorder ON" in m
    assert int(re.search(r"(\d+) events", m).group(1)) > 0
    # rc 0 means the DUAL gate held (best-vs-best OR paired median —
    # under box noise the best ratio alone can dip while the paired
    # median clears, so no second hard floor here); vs_baseline stays
    # a sanity check that both legs measured something.
    assert payload["vs_baseline"] > 0
    assert list(out.parent.glob("*.tmp.*")) == []


def _hist(args, cwd):
    return subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "bench_history.py"), *args],
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=60,
    )


def test_bench_history_unreachable_rounds_are_no_data(tmp_path):
    """The satellite's one hard rule: a CHIP UNREACHABLE round (rc != 0
    / status chip-unreachable / legacy 0.0-value row) is NO-DATA —
    never a 0-tok/s measurement that fires the regression gate."""

    def write(n, doc):
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(doc))

    ok = {
        "rc": 0,
        "parsed": {
            "metric": "candidate-tokens/sec/chip (x)",
            "value": 100.0,
            "unit": "tokens/sec/chip",
            "status": "ok",
        },
    }
    # Legacy unreachable row (pre-PR-10: no status field, rc != 0,
    # 0.0 value) AND the new explicit form.
    legacy_dead = {
        "rc": 2,
        "parsed": {
            "metric": "CHIP UNREACHABLE (probe timeout)",
            "value": 0.0,
            "unit": "tokens/sec/chip",
        },
    }
    new_dead = {
        "rc": 2,
        "parsed": {
            "metric": "CHIP UNREACHABLE (probe timeout)",
            "value": 0.0,
            "unit": "tokens/sec/chip",
            "status": "chip-unreachable",
        },
    }
    write(1, ok)
    write(2, legacy_dead)
    write(3, new_dead)
    r = _hist(["--dir", str(tmp_path), "--check", "--json"], tmp_path)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert [b["status"] for b in doc["bench"]] == [
        "ok", "chip-unreachable", "chip-unreachable",
    ]
    # Latest round is an outage: verdict stale, gate passes, and the
    # last MEASUREMENT (not 0.0) is what the trajectory reports.
    assert doc["verdict"]["verdict"] == "stale"
    assert doc["verdict"]["latest_value"] == 100.0

    # A real regression on a measured round DOES fail the gate...
    write(4, json.loads(json.dumps(ok).replace("100.0", "50.0")))
    r = _hist(["--dir", str(tmp_path), "--check"], tmp_path)
    assert r.returncode == 1
    assert "regression" in r.stdout
    # ...and a recovered round passes again.
    write(5, json.loads(json.dumps(ok).replace("100.0", "97.0")))
    r = _hist(["--dir", str(tmp_path), "--check"], tmp_path)
    assert r.returncode == 0, r.stdout

    # No measured rounds at all: no-data, never an error.
    for p in tmp_path.glob("BENCH_r*.json"):
        p.unlink()
    write(1, legacy_dead)
    r = _hist(["--dir", str(tmp_path), "--check"], tmp_path)
    assert r.returncode == 0
    assert "no-data" in r.stdout

    # A malformed value is an artifact-format problem — still
    # no-data, never a gate-crashing traceback.
    write(2, {"rc": 0, "parsed": {"metric": "m", "value": "n/a"}})
    r = _hist(["--dir", str(tmp_path), "--check", "--json"], tmp_path)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc["bench"][-1]["status"] == "no-data"


def test_bench_history_real_repo_artifacts():
    """The committed r01..r05 artifacts parse: r03 is the only
    measured bench round (23.8k), r04/r05 are unreachable no-data —
    and the CI gate passes on the repo as it stands."""
    r = _hist(["--check", "--json"], ROOT)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    by_round = {b["round"]: b for b in doc["bench"]}
    assert by_round[3]["status"] == "ok"
    assert by_round[3]["value"] == pytest.approx(23800.22)
    assert by_round[4]["status"] == "chip-unreachable"
    assert by_round[5]["status"] == "chip-unreachable"
    assert doc["verdict"]["verdict"] in ("stale", "ok")
