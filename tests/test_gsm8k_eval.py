"""Real-data GSM8K path (VERDICT r2 #6).

The bundled ``eval/data/gsm8k_mini.jsonl`` is a 50-problem dataset in the
exact GSM8K JSONL schema ({"question", "answer": "...#### N"}) — this
zero-egress environment cannot download the real corpus, so the file is
authored in-format; the *harness* (load -> prompt -> engine -> vote -> EM)
is the thing under test, per the reference's absent test story
(SURVEY.md §4).

The HF-tokenizer leg builds a genuine ``transformers``
``PreTrainedTokenizerFast`` offline (a BPE trained on the dataset corpus
via the ``tokenizers`` library), so ``evaluate_self_consistency`` is
exercised through the same tokenizer class a real checkpoint would use —
not just the byte fallback.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer, HFTokenizer
from llm_consensus_tpu.eval.gsm8k import (
    evaluate_self_consistency,
    exact_match,
    load_gsm8k,
)
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params

DATA = (
    Path(__file__).parent.parent
    / "llm_consensus_tpu"
    / "eval"
    / "data"
    / "gsm8k_mini.jsonl"
)


def test_bundled_dataset_loads_and_golds_extract():
    problems = load_gsm8k(DATA)
    assert len(problems) == 50
    from llm_consensus_tpu.consensus.voting import extract_final_number

    for p in problems:
        assert "####" in p.answer
        gold = extract_final_number(p.answer)
        assert gold is not None
        assert exact_match(gold, p.answer)


def test_load_gsm8k_limit():
    assert len(load_gsm8k(DATA, limit=7)) == 7


@pytest.fixture(scope="module")
def hf_tokenizer(tmp_path_factory):
    """A real transformers PreTrainedTokenizerFast, built offline: BPE
    trained on the bundled GSM8K corpus (vocab fits test-tiny's 384)."""
    from tokenizers import Tokenizer
    from tokenizers.models import BPE
    from tokenizers.pre_tokenizers import Whitespace
    from tokenizers.trainers import BpeTrainer
    from transformers import PreTrainedTokenizerFast

    corpus = [
        json.loads(line)["question"] + " " + json.loads(line)["answer"]
        for line in DATA.read_text().splitlines()
    ]
    tok = Tokenizer(BPE(unk_token="<unk>"))
    tok.pre_tokenizer = Whitespace()
    trainer = BpeTrainer(
        vocab_size=380,
        special_tokens=["<pad>", "<s>", "</s>", "<unk>"],
    )
    tok.train_from_iterator(corpus, trainer)
    path = tmp_path_factory.mktemp("tok") / "tokenizer.json"
    tok.save(str(path))
    fast = PreTrainedTokenizerFast(
        tokenizer_file=str(path),
        bos_token="<s>",
        eos_token="</s>",
        pad_token="<pad>",
        unk_token="<unk>",
    )
    return HFTokenizer(fast)


def test_hf_tokenizer_roundtrip(hf_tokenizer):
    text = "Jordan buys 5 notebooks and pays with a $100 bill."
    ids = hf_tokenizer.encode(text)
    assert ids[0] == hf_tokenizer.bos_id
    assert all(0 <= i < hf_tokenizer.vocab_size for i in ids)
    # BPE on whitespace pre-tokenization loses only spacing fidelity.
    assert "notebooks" in hf_tokenizer.decode(ids)


def test_eval_harness_end_to_end_hf_tokenizer(hf_tokenizer):
    """evaluate_self_consistency over real-format data with a real HF
    tokenizer class and a tiny random model: the EM plumbing (prompting,
    batched N-way sampling, answer extraction, voting) runs end to end."""
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    engine = InferenceEngine(
        cfg,
        params,
        tokenizer=hf_tokenizer,
        engine_config=EngineConfig(
            max_new_tokens=8, seq_buckets=(64, 128), batch_buckets=(1, 2, 4)
        ),
    )
    problems = load_gsm8k(DATA, limit=3)
    report = evaluate_self_consistency(
        engine, problems, n=4, temperature=0.8, seed=0, max_new_tokens=8
    )
    assert report.n_problems == 3
    assert report.n_candidates == 4
    assert 0.0 <= report.em <= 1.0
    assert report.total_candidate_tokens > 0
    assert len(report.per_problem) == 3
    # A random model answering real problems should essentially never be
    # right; the point is the harness ran, not the score.
    d = report.to_dict()
    assert set(d) >= {"em", "n_problems", "candidate_tokens_per_sec"}


def test_dataset_has_no_duplicate_problems():
    problems = load_gsm8k(DATA)
    assert len({p.question for p in problems}) == len(problems)


def test_em_rises_with_n_on_bundled_data():
    """Majority vote over a 60%-accurate candidate stream: EM at N=9
    must beat EM at N=1 on the bundled 50 problems (the self-consistency
    effect the north-star metric measures). OracleEngine is the shared
    eval helper — the same stream examples/gsm8k_em_vs_n.py records."""
    from llm_consensus_tpu.eval.gsm8k import OracleEngine

    problems = load_gsm8k(DATA)
    em = {}
    for n in (1, 9):
        engine = OracleEngine(problems, p_correct=0.6)
        report = evaluate_self_consistency(
            engine, problems, n=n, temperature=0.7, seed=0
        )
        em[n] = report.em
    assert em[9] > em[1]
    assert em[9] >= 0.8  # majority of 9 at p=.6 is right ~73%+ of the time


def test_eval_rides_prefix_cache_across_problems():
    """One header prefill serves every problem and both sweep points."""
    from llm_consensus_tpu.eval.gsm8k import (
        evaluate_self_consistency,
        few_shot_header,
        synthetic_problems,
    )

    # Enough context for a 2-shot header (~300 byte tokens) + question.
    cfg = get_config("test-tiny").with_(max_seq_len=1024)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    engine = InferenceEngine(
        cfg,
        params,
        engine_config=EngineConfig(
            max_new_tokens=6, seq_buckets=(64, 128, 256),
            batch_buckets=(1, 2, 4),
        ),
    )
    shots = synthetic_problems(2, seed=99)
    problems = synthetic_problems(3, seed=1)
    r1 = evaluate_self_consistency(
        engine, problems, n=2, temperature=0.8, few_shot=shots
    )
    assert engine.prefix_cache.stats.misses == 1
    assert engine.prefix_cache.stats.hits == len(problems) - 1
    # Second sweep point (different N): header K/V still cached.
    evaluate_self_consistency(
        engine, problems, n=4, temperature=0.8, few_shot=shots
    )
    assert engine.prefix_cache.stats.misses == 1
    assert r1.n_problems == 3
    hdr = few_shot_header(shots)
    assert all(f"{ex.question}" in hdr for ex in shots)
