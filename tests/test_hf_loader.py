"""HF safetensors loading (models/hf_loader.py): logit parity with
transformers' own torch implementations on tiny random checkpoints.

This is the strongest correctness anchor for the transformer: identical
weights must give (near-)identical logits for every supported family —
Llama (GQA), Qwen2 (qkv bias), Mixtral (MoE).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from llm_consensus_tpu.models.hf_loader import (  # noqa: E402
    config_from_hf,
    load_hf_params,
)
from llm_consensus_tpu.models.transformer import forward  # noqa: E402


def _save_hf(tmp_path, model):
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    return tmp_path


def _parity(tmp_path, hf_model, batch=2, seq=12, tol=2e-2):
    path = _save_hf(tmp_path, hf_model)
    cfg = config_from_hf(path, name="tiny-hf")
    params = load_hf_params(cfg, path, dtype=jnp.float32)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (batch, seq))
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.float().numpy()
    ours = np.asarray(forward(cfg, params, jnp.asarray(tokens)))
    # Compare softmax-space (logit offsets don't matter) and argmax.
    assert ours.shape == ref.shape
    np.testing.assert_allclose(
        jax.nn.softmax(ours, axis=-1),
        torch.softmax(torch.tensor(ref), dim=-1).numpy(),
        atol=tol,
    )
    assert (ours.argmax(-1) == ref.argmax(-1)).mean() > 0.97


def test_llama_parity(tmp_path):
    config = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    _parity(tmp_path, transformers.LlamaForCausalLM(config))


def test_qwen2_parity(tmp_path):
    config = transformers.Qwen2Config(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    _parity(tmp_path, transformers.Qwen2ForCausalLM(config))


def test_mixtral_parity(tmp_path):
    config = transformers.MixtralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    torch.manual_seed(2)
    _parity(tmp_path, transformers.MixtralForCausalLM(config))


def test_tied_embeddings(tmp_path):
    config = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        tie_word_embeddings=True,
    )
    torch.manual_seed(3)
    _parity(tmp_path, transformers.LlamaForCausalLM(config))


def test_mistral_sliding_window_parity(tmp_path):
    """Window smaller than the sequence so windowed masking is exercised."""
    config = transformers.MistralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        sliding_window=4,
        max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    torch.manual_seed(5)
    model = transformers.MistralForCausalLM(config)
    path = _save_hf(tmp_path, model)
    cfg = config_from_hf(path)
    assert cfg.sliding_window == 4
    _parity(tmp_path, model, seq=12)


def test_llama31_rope_scaling_parity(tmp_path):
    config = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 16,
        },
        tie_word_embeddings=False,
    )
    torch.manual_seed(6)
    model = transformers.LlamaForCausalLM(config)
    cfg = config_from_hf(_save_hf(tmp_path, model))
    assert cfg.rope_scaling is not None and cfg.rope_scaling.factor == 8.0
    _parity(tmp_path, model, seq=20)


def test_unsupported_rope_scaling_raises(tmp_path):
    config = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        rope_scaling={"rope_type": "yarn", "factor": 2.0},
    )
    torch.manual_seed(7)
    path = _save_hf(tmp_path, transformers.LlamaForCausalLM(config))
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(path)


def test_config_mismatch_raises(tmp_path):
    config = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
    )
    torch.manual_seed(4)
    path = _save_hf(tmp_path, transformers.LlamaForCausalLM(config))
    cfg = config_from_hf(path).with_(n_layers=4)
    with pytest.raises(KeyError):
        load_hf_params(cfg, path)
