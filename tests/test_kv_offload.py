"""Hierarchical KV cache: host-RAM offload tier (PR 4).

Covers the byte-budgeted :class:`HostPageStore` (LRU, verbatim planes,
overflow drops), the ``install_page`` promote primitive (demote→restore
round trips bit-identical to fresh prefill, bf16 AND int8-with-scales),
the ``reclaimable_pages`` invariant repair, and the ContinuousBatcher
end to end: eviction demotes, a later same-prefix admission restores
instead of re-prefilling (byte-identical pages, identical text), a
concurrent burst dedups against the in-flight restore, and the CPU-run
``bench.py --serve-offload`` A/B leg lands ≥1 restore with prefill
tokens saved and unchanged output.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.engine import plan_memory
from llm_consensus_tpu.models.cache import quantize_kv
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.paged_cache import (
    PagedKVCache,
    PagePool,
    PrefixRegistry,
    install_page,
)
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)
from llm_consensus_tpu.serving.offload import HostPageStore, page_planes

CFG = get_config("test-tiny")


def _params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _planes(*shapes_dtypes, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for shape, dt in shapes_dtypes:
        a = rng.standard_normal(shape) * 10
        out.append(a.astype(dt))
    return tuple(out)


# ---------------------------------------------------------------------------
# HostPageStore: LRU + byte budget + verbatim planes
# ---------------------------------------------------------------------------


def test_host_store_budget_drops_lru_cleanly():
    page = _planes(((4, 8), np.float32))  # 128 B/page
    store = HostPageStore(budget_bytes=3 * 128)
    for i in range(3):
        assert store.put(("chain", i), _planes(((4, 8), np.float32), seed=i))
    assert len(store) == 3 and store.bytes_used == 3 * 128
    # Refresh chain 0 (MRU), then overflow: chain 1 is now LRU and drops.
    assert store.get(("chain", 0)) is not None
    assert store.put(("chain", 3), page)
    assert len(store) == 3 and store.bytes_used == 3 * 128
    assert ("chain", 1) not in store
    assert ("chain", 0) in store and ("chain", 2) in store
    assert store.dropped_pages == 1
    # A page bigger than the whole budget is refused, not thrashed in.
    assert not store.put(("huge",), _planes(((40, 80), np.float32)))
    assert ("huge",) not in store and len(store) == 3
    assert store.dropped_pages == 2
    # touch() refreshes recency without re-fetching content.
    store.touch(("chain", 2))
    store.put(("chain", 4), page)
    assert ("chain", 0) not in store and ("chain", 2) in store
    assert store.demoted_pages == 6  # 5 puts that landed + 1 touch


def test_host_store_roundtrips_int8_planes_with_scales_verbatim():
    """int8-KV pages spill VERBATIM with their scales: same dtype, same
    bytes back — the store never recompresses or casts."""
    k = np.random.default_rng(0).standard_normal((2, 8, 2, 4))
    kq, ks = quantize_kv(jnp.asarray(k, jnp.float32))
    planes = (
        np.asarray(kq),
        np.asarray(kq)[::-1].copy(),
        np.asarray(ks),
        np.asarray(ks) + 1,
    )
    store = HostPageStore(budget_bytes=1 << 20)
    assert store.put(("q",), planes)
    got = store.get(("q",))
    assert store.hits == 1 and store.lookups == 1
    for a, b in zip(planes, got):
        assert a.dtype == b.dtype
        assert a.tobytes() == b.tobytes()
    assert got[0].dtype == np.int8 and got[2].dtype == np.float32
    assert store.get(("missing",)) is None
    assert store.lookups == 2 and store.hits == 1


# ---------------------------------------------------------------------------
# Demote → restore round trip at the cache level (bf16 and int8 pools)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.int8])
def test_page_round_trip_bit_identical_to_fresh(dtype):
    """A page that leaves through page_planes and returns through
    install_page must be BIT-identical to the freshly-written one —
    bf16 pools and int8-KV pools alike (int8 values pass verbatim; a
    quant pool's scale planes ride the store unchanged, covered
    above)."""
    cache = PagedKVCache.create(
        CFG, n_pages=4, page_size=8, max_seqs=2, pages_per_seq=2,
        dtype=dtype,
    )
    rng = np.random.default_rng(1)
    fresh = rng.standard_normal(cache.k.shape) * 100
    cache = PagedKVCache(
        k=jnp.asarray(fresh).astype(dtype),
        v=jnp.asarray(fresh[::-1].copy()).astype(dtype),
        page_table=cache.page_table,
        length=cache.length,
    )
    want_k = np.asarray(cache.k[:, 2])
    want_v = np.asarray(cache.v[:, 2])

    store = HostPageStore(budget_bytes=1 << 24)
    store.put(("c",), page_planes(cache, 2))
    # Destroy the device copy (eviction), then promote back from host.
    zero = jnp.zeros_like(cache.k[:, 2])
    cache = PagedKVCache(
        k=cache.k.at[:, 2].set(zero),
        v=cache.v.at[:, 2].set(zero),
        page_table=cache.page_table,
        length=cache.length,
    )
    pk, pv = store.get(("c",))
    cache = install_page(cache, jnp.int32(2), jnp.asarray(pk), jnp.asarray(pv))
    assert np.asarray(cache.k[:, 2]).tobytes() == want_k.tobytes()
    assert np.asarray(cache.v[:, 2]).tobytes() == want_v.tobytes()


# ---------------------------------------------------------------------------
# reclaimable_pages invariant (the PR-3 stats drift)
# ---------------------------------------------------------------------------


def test_reclaimable_counts_only_what_evict_can_free():
    """The drift: a registry-only parent above a child some live
    sequence still maps is NOT freeable (evict drops childless leaves
    only) and must not be counted — reclaimable_pages() must equal
    exactly what evict(∞) frees, and pool accounting must balance."""
    pool = PagePool(range(1, 8))
    reg = PrefixRegistry(pool, 4)
    ids = list(range(100, 112))  # 3 full pages: chain A -> B -> C
    pages = pool.alloc(3)
    created = reg.register(ids, pages)
    for node, _ in created:
        reg.mark_ready(node)
    # The "sequence" keeps only B mapped; A and C are registry-only.
    pool.release(pages[0])
    pool.release(pages[2])
    # C (leaf, rc 1) is evictable; A (rc 1) sits ABOVE pinned B and is
    # not reachable by leaf eviction while B lives.
    want = reg.reclaimable_pages()
    assert want == 1
    total = 7
    pinned = pool.held - want  # pages some holder other than evict() pins
    assert pool.available + pinned + want == total
    assert reg.evict(999) == want
    assert reg.reclaimable_pages() == 0
    # The sequence retires B: now B (leaf) then A (exposed parent) free.
    pool.release(pages[1])
    want2 = reg.reclaimable_pages()
    assert want2 == 2
    assert reg.evict(999) == want2
    assert pool.available == total


# ---------------------------------------------------------------------------
# ContinuousBatcher end to end
# ---------------------------------------------------------------------------

_HEADER = "Panel shared header for every persona, forty ch: "  # 49 chars
_FILLERS = [
    f"{i} unique filler prompt with plenty of padding text."
    for i in range(3)
]
# Starved pool: 10 usable pages vs a 5-page unshared request — cached
# prefixes cannot survive a filler round device-side.
_OCFG = dict(
    max_slots=2,
    page_size=16,
    n_pages=11,
    pages_per_seq=8,
    max_new_tokens=4,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
)


def _serve(batcher, prompts, **kw):
    futs = [batcher.submit(p, **kw) for p in prompts]
    return [f.result(timeout=120).text for f in futs]


def _rounds(batcher):
    """The multi-round panel shape: header burst, unique-prefix filler
    burst (forces eviction of the header's registry pages), header
    re-vote burst."""
    out = [_serve(batcher, [_HEADER + f"q{i}" for i in range(3)])]
    out.append(_serve(batcher, _FILLERS))
    out.append(_serve(batcher, [_HEADER + f"r{i}" for i in range(3)]))
    return out


def test_offload_restore_matches_fresh_prefill_end_to_end():
    """The acceptance criterion: the same 3-round traffic served with
    the host tier ON (round 3 RESTORES the demoted header) and OFF
    (round 3 re-prefills it) produces byte-identical text — and the
    restored device page holds exactly the bytes the fresh prefill
    wrote (compared via the spilled host copy, which install_page
    writes back verbatim)."""
    params = _params()
    b_off = ContinuousBatcher(
        CFG, params, config=ContinuousConfig(**_OCFG, host_cache_bytes=0)
    )
    try:
        want = _rounds(b_off)
        s_off = b_off.stats()
    finally:
        b_off.close()
    assert s_off["offload_demoted_pages"] == 0
    assert s_off["prefix_evictions"] > 0  # the pool really is starved

    b_on = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**_OCFG, host_cache_bytes=64 << 20),
    )
    try:
        got = [_serve(b_on, [_HEADER + f"q{i}" for i in range(3)])]
        # Fresh-prefill bytes of the header's first page, read before
        # the filler round evicts it.
        reg = b_on._registries[0]
        ids = b_on.tokenizer.encode(_HEADER + "q0")
        key0 = tuple(int(t) for t in ids[:16])
        node0 = reg._root.children[key0]
        fresh = page_planes(b_on.cache, node0.page)
        got.append(_serve(b_on, _FILLERS))
        got.append(_serve(b_on, [_HEADER + f"r{i}" for i in range(3)]))
        s_on = b_on.stats()
        # Round 3 re-registered the restored header page under a fresh
        # page id; its device content must be bit-equal to the fresh
        # prefill's.
        node1 = reg._root.children[key0]
        restored = page_planes(b_on.cache, node1.page)
    finally:
        b_on.close()

    assert got == want
    assert s_on["offload_demoted_pages"] > 0
    assert s_on["offload_restored_pages"] >= 3  # the header's full pages
    assert s_on["offload_dropped_pages"] == 0
    assert s_on["offload_host_bytes"] > 0
    # Restores replaced prefill work: fewer chunks than the off leg.
    assert s_on["prefill_chunks"] < s_off["prefill_chunks"]
    assert s_on["free_pages"] == s_on["total_pages"]
    for a, b in zip(fresh, restored):
        assert a.tobytes() == b.tobytes()


def test_concurrent_burst_dedups_against_inflight_restore():
    """The panel re-vote submitted ALL AT ONCE after the header was
    demoted: the first admission schedules the restore; burst-mates
    must dedup against the IN-FLIGHT restore through the same
    readiness gates as an in-flight prefill — the header's pages
    restore exactly once, not once per request."""
    from llm_consensus_tpu.server.metrics import (
        KV_OFFLOAD_RESTORED,
        KV_RESTORE_SECONDS,
    )

    params = _params()
    b = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**_OCFG, host_cache_bytes=64 << 20),
    )
    try:
        seq = [_serve(b, [_HEADER + f"q{i}"])[0] for i in range(3)]
        _serve(b, _FILLERS)
        assert b.stats()["offload_demoted_pages"] > 0
        before = b.stats()["offload_restored_pages"]
        m_before = KV_OFFLOAD_RESTORED.value
        h_before = KV_RESTORE_SECONDS.count
        # Same prompts, same seeds, submitted concurrently this time.
        burst = _serve(b, [_HEADER + f"q{i}" for i in range(3)])
        stats = b.stats()
    finally:
        b.close()
    assert burst == seq
    restored = stats["offload_restored_pages"] - before
    # 3 full header pages of the q0 prompt (49+1 ids, page 16) restore
    # ONCE; the other 2 burst-mates map them (shared, not restored).
    assert restored == 3
    # Prometheus families moved in lockstep with the batcher's stats.
    assert KV_OFFLOAD_RESTORED.value - m_before == restored
    assert KV_RESTORE_SECONDS.count - h_before == restored


def test_offload_disabled_without_sharing_or_chunking():
    """The tier needs the chunked shared-prefix path (restores ride
    its readiness gates): a legacy-config batcher silently runs
    without it rather than half-engaging."""
    params = _params()
    b = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(
            **{**_OCFG, "prefill_chunk": 0, "share_prefix": False},
            host_cache_bytes=64 << 20,
        ),
    )
    try:
        assert b._offload is None
        out = _serve(b, [_HEADER + "legacy"])
    finally:
        b.close()
    assert len(out) == 1 and isinstance(out[0], str)


def test_plan_memory_includes_host_tier():
    plan = plan_memory(
        CFG, kv_quant=False, host_cache_bytes=1 << 20, page_size=64
    )
    assert plan["host_cache_bytes"] == 1 << 20
    assert plan["host_capacity_pages"] == (1 << 20) // plan["host_page_bytes"]
    assert plan["host_capacity_tokens"] == plan["host_capacity_pages"] * 64
    # int8-KV pages (scales included) are smaller, so more fit.
    q = plan_memory(
        CFG, kv_quant=True, host_cache_bytes=1 << 20, page_size=64
    )
    assert q["host_page_bytes"] < plan["host_page_bytes"]
    assert q["host_capacity_pages"] > plan["host_capacity_pages"]
    # Host RAM never changes the device-fit verdict.
    base = plan_memory(CFG, hbm_bytes=16 << 30)
    tiered = plan_memory(
        CFG, hbm_bytes=16 << 30, host_cache_bytes=1 << 30
    )
    assert tiered["fits"] == base["fits"]
    assert tiered["total_bytes"] == base["total_bytes"]
    assert "host_cache_bytes" not in base  # opt-in output


def test_bench_serve_offload_cpu_ab_leg(tmp_path: Path):
    """The CPU-run A/B leg (acceptance): ≥1 restored prefix page,
    prefill tokens saved > 0, text byte-identical to the tier-off leg,
    rc 0 — and the artifact lands ATOMICALLY at --out (tmp +
    os.replace; no torn 0-byte files, the round-5 failure mode)."""
    out = tmp_path / "reports" / "offload_ab.json"
    r = subprocess.run(
        [
            sys.executable, "bench.py", "--tiny", "--cpu",
            "--serve-offload", "--serve-requests", "3",
            "--serve-slots", "2", "--new-tokens", "6",
            "--prompt-len", "64", "--serve-chunk", "1",
            "--serve-prefill-chunk", "64", "--out", str(out),
        ],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads(out.read_text())
    assert payload == json.loads(r.stdout.strip().splitlines()[-1])
    assert payload["value"] > 0
    m = payload["metric"]
    assert int(re.search(r"restored (\d+)", m).group(1)) >= 1
    assert int(re.search(r"prefill tokens saved (\d+)", m).group(1)) > 0
    assert "text unchanged=True" in m
    # No tmp turds left behind by the atomic write.
    assert list(out.parent.glob("*.tmp.*")) == []
