"""int8 KV cache (models/cache.QuantKVCache) + its decode attention.

Decode re-reads the whole cache every step; int8 halves that HBM term.
Correctness anchors: greedy decode parity with the bf16 cache, and the
Pallas q8 kernel (interpret mode) against the jnp dequant reference.
"""

import jax
import jax.numpy as jnp
import numpy as np

from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.engine.generate import generate
from llm_consensus_tpu.models.cache import QuantKVCache, quantize_kv
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.ops.attention import decode_attention_quant
from llm_consensus_tpu.ops.pallas.attention import flash_decode_attention_q8

CFG = get_config("test-tiny")


def test_quantize_kv_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 3, 16), jnp.float32)
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (2, 5, 3)
    err = jnp.abs(q.astype(jnp.float32) * s[..., None] - x)
    assert float(jnp.max(err - s[..., None] / 2)) < 1e-6


def test_greedy_decode_parity_with_bf16_cache():
    params = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 12), 5, 200)
    lengths = jnp.full((4,), 12, jnp.int32)
    temps = jnp.zeros((4,), jnp.float32)
    kw = dict(max_new_tokens=12, eos_id=-1)
    ref = generate(
        CFG, params, tokens, lengths, jax.random.PRNGKey(0), temps, **kw
    )
    out = generate(
        CFG,
        params,
        tokens,
        lengths,
        jax.random.PRNGKey(0),
        temps,
        kv_quant=True,
        **kw,
    )
    agree = float((ref.tokens == out.tokens).mean())
    assert agree > 0.9  # tiny random model: tolerate rare tie flips


def test_q8_kernel_matches_jnp_reference():
    b, hkv, g, s, d = 2, 2, 2, 16, 8
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, 1, hkv * g, d), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, hkv, s, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, s, d))
    k_q, k_s = quantize_kv(k)
    v_q, v_s = quantize_kv(v)
    valid = jnp.asarray([5, 16], jnp.int32)
    ref = decode_attention_quant(q, k_q, k_s, v_q, v_s, valid)
    out = flash_decode_attention_q8(
        q, k_q, k_s, v_q, v_s, valid, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-2, rtol=2e-2
    )


def test_engine_kv_quant_end_to_end():
    params = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = InferenceEngine(
        CFG,
        params,
        engine_config=EngineConfig(
            quant="int8", kv_quant=True, max_new_tokens=6
        ),
    )
    out = eng.generate_texts(["hello", "world"], max_new_tokens=6)
    assert len(out) == 2 and all(isinstance(r.text, str) for r in out)


def test_quant_cache_shapes():
    cache = QuantKVCache.create(CFG, batch=3, max_len=32)
    assert cache.k_q.shape == (
        CFG.n_layers,
        3,
        CFG.n_kv_heads,
        32,
        CFG.head_dim,
    )
    assert cache.k_scale.shape == (CFG.n_layers, 3, CFG.n_kv_heads, 32)
    assert cache.max_len == 32
    assert int(cache.advanced(2).length[0]) == 2


def test_q8_kernel_per_head_fallback_matches_row_kernel(monkeypatch):
    """Both dispatch branches (batch-row program vs per-(batch, head)
    program) must agree with the jnp reference."""
    import llm_consensus_tpu.ops.pallas.attention as pattn

    b, hkv, g, s, d = 2, 2, 2, 16, 8
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (b, 1, hkv * g, d), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, hkv, s, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, s, d))
    k_q, k_s = quantize_kv(k)
    v_q, v_s = quantize_kv(v)
    valid = jnp.asarray([7, 12], jnp.int32)
    ref = decode_attention_quant(q, k_q, k_s, v_q, v_s, valid)
    row = flash_decode_attention_q8(
        q, k_q, k_s, v_q, v_s, valid, interpret=True
    )
    monkeypatch.setattr(pattn, "_ROW_KERNEL_MAX_KV_BYTES", 0)
    per_head = flash_decode_attention_q8(
        q, k_q, k_s, v_q, v_s, valid, interpret=True
    )
    np.testing.assert_allclose(np.asarray(row), np.asarray(ref), atol=2e-2, rtol=2e-2)
    np.testing.assert_allclose(np.asarray(per_head), np.asarray(ref), atol=2e-2, rtol=2e-2)


def test_stacked_decode_path_matches_default():
    """The opt-in stacked-cache decode path must produce the same tokens
    as the default slice+row-kernel path."""
    import llm_consensus_tpu.models.transformer as tr
    from llm_consensus_tpu.engine.generate import generate
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.array([[5, 9, 13, 17, 2, 0, 0, 0]], jnp.int32)
    lengths = jnp.array([5], jnp.int32)

    def run():
        return generate(
            cfg, params, tokens, lengths, jax.random.PRNGKey(1),
            jnp.zeros(1), max_new_tokens=6, kv_quant=True,
        )

    base = run()
    tr.set_stacked_decode(True)
    try:
        jax.clear_caches()
        stacked = run()
    finally:
        tr.set_stacked_decode(False)
        jax.clear_caches()
    assert base.tokens.tolist() == stacked.tokens.tolist()
    np.testing.assert_allclose(
        base.logprob_sum, stacked.logprob_sum, rtol=2e-2, atol=2e-2
    )


def test_q8_stacked_kernel_matches_row_kernel():
    """The stacked-cache kernel itself (scalar-prefetch index_maps), in
    interpret mode, against the sliced row kernel across layers and
    ragged lengths — and the VMEM-budget fallback branch."""
    import llm_consensus_tpu.ops.pallas.attention as pattn
    from llm_consensus_tpu.ops.pallas.attention import (
        flash_decode_attention_q8_stacked,
    )

    n_layers, b, hkv, g, s, d = 3, 2, 2, 2, 16, 8
    key = jax.random.PRNGKey(7)
    q = jax.random.normal(key, (b, 1, hkv * g, d), jnp.float32)
    k5 = jax.random.normal(
        jax.random.fold_in(key, 1), (n_layers, b, hkv, s, d)
    )
    v5 = jax.random.normal(
        jax.random.fold_in(key, 2), (n_layers, b, hkv, s, d)
    )
    kq5, ks5 = quantize_kv(k5)
    vq5, vs5 = quantize_kv(v5)
    valid = jnp.asarray([5, 14], jnp.int32)
    for layer in range(n_layers):
        want = flash_decode_attention_q8(
            q, kq5[layer], ks5[layer], vq5[layer], vs5[layer], valid,
            interpret=True,
        )
        got = flash_decode_attention_q8_stacked(
            q, kq5, ks5, vq5, vs5, valid, jnp.asarray(layer),
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )
    # Budget fallback branch (dynamic-slice + sliced kernel).
    orig = pattn._ROW_KERNEL_MAX_KV_BYTES
    pattn._ROW_KERNEL_MAX_KV_BYTES = 0
    try:
        fb = flash_decode_attention_q8_stacked(
            q, kq5, ks5, vq5, vs5, valid, jnp.asarray(1), interpret=True
        )
    finally:
        pattn._ROW_KERNEL_MAX_KV_BYTES = orig
    want1 = flash_decode_attention_q8(
        q, kq5[1], ks5[1], vq5[1], vs5[1], valid, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(fb), np.asarray(want1), atol=2e-2, rtol=2e-2
    )
