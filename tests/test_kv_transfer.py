"""Zero-copy pipelined KV movement plane (PR 17).

Covers the v2 scatter-gather wire format end to end: multi-dtype plane
round trips land bit-exact through ``sendmsg``/``recv_into`` with NO
pickled plane bytes, v1 and v2 clients interoperate against one server,
the batched ops (``put_many``/``get_run``/``touch_many``/``run_len``)
keep per-key semantics in one round trip, pipelined concurrent ops over
a single connection dispatch by sequence tag under thread pressure,
the ``gateway_transfer_bytes_total`` family moves in lockstep with the
client's tx/rx mirrors, fuzzed/truncated frames drop one connection
without wedging the server, ``_send_vec`` survives partial sends and
iovec chunking against a slow consumer, TCP_NODELAY is set on both
ends, and a server killed mid-pipeline fails every in-flight op to a
miss (the circuit-breaker degrade contract). The serving-layer half:
streamed exports spill incrementally and respect their deadline,
route-driven prefetch stages store pages ahead of admission (consumed
as restore-plan hits) while a wrong/cold guess falls through to
recompute with byte-identical text, and the handoff-latency histogram
moves in lockstep with fleet stats on a roled fleet.
"""

import hashlib
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.server.metrics import (
    HANDOFF_SECONDS,
    KV_PREFETCH,
    TRANSFER_BYTES,
)
from llm_consensus_tpu.serving import flight
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)
from llm_consensus_tpu.serving.fleet import FleetConfig, ReplicaSet
from llm_consensus_tpu.serving.offload import HostPageStore
from llm_consensus_tpu.serving.remote_store import (
    _IOV_MAX,
    _LEN,
    _MAGIC,
    _PRELUDE,
    PageStoreServer,
    RemotePageStore,
    _send_vec,
    parse_endpoint,
)

CFG = get_config("test-tiny")

# 49 chars -> 3 full 16-token pages + a tail at page_size 16.
_HEADER = "Panel shared header for every persona, forty ch: "

_SCFG = dict(
    max_slots=2,
    page_size=16,
    n_pages=32,
    pages_per_seq=8,
    max_new_tokens=4,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
    host_cache_bytes=64 << 20,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _serve(target, prompts, **kw):
    futs = [target.submit(p, **kw) for p in prompts]
    return [f.result(timeout=300).text for f in futs]


def _planes(seed=0, kib=1):
    """A 2-plane bf16-ish page entry: bf16 K plane (the pool's real
    dtype, an ml_dtypes extension type numpy can't name natively) and
    an f32 V plane — the dtype-by-NAME wire contract's hard case."""
    import ml_dtypes

    rng = np.random.default_rng(seed)
    k = (rng.standard_normal(kib * 512) * 4).astype(ml_dtypes.bfloat16)
    v = (rng.standard_normal(kib * 256) * 4).astype(np.float32)
    return (k, v)


def _bits(planes):
    return tuple((p.dtype.name, p.shape, p.tobytes()) for p in planes)


def _live_pair(**kw):
    store = HostPageStore(budget_bytes=64 << 20)
    server = PageStoreServer(store).start()
    client = RemotePageStore(server.endpoint, timeout_s=10.0, **kw)
    return store, server, client


# ---------------------------------------------------------------------------
# Wire v2: zero-copy round trips, interop, batched ops
# ---------------------------------------------------------------------------


def test_wire_v2_round_trip_multi_dtype():
    """bf16 + f32 + int8-with-scales entries cross the scatter-gather
    wire bit-exact, dtypes resolved by name through ml_dtypes."""
    store, server, client = _live_pair()
    try:
        pages = {
            ("chain", 0): _planes(seed=0),
            ("chain", 1): _planes(seed=1),
            ("int8", 0): (
                np.arange(-64, 64, dtype=np.int8).reshape(8, 16),
                np.linspace(0.1, 2.0, 8, dtype=np.float32),
            ),
        }
        for key, planes in pages.items():
            resident, demoted, dropped = client.put_counted(key, planes)
            assert resident and demoted == 1 and dropped == 0
        for key, planes in pages.items():
            assert key in client
            got = client.get(key)
            assert _bits(got) == _bits(planes)
        assert client.get(("missing",)) is None
        # The authoritative copy landed server-side, verbatim.
        assert _bits(store.get(("chain", 0))) == _bits(pages[("chain", 0)])
        assert len(client) == 3  # piggybacked stats cache
    finally:
        client.close()
        server.close()


def test_wire_v1_v2_interop_one_server():
    """The server speaks both formats per frame: pages put by either
    client read back bit-exact through the other."""
    store, server, c2 = _live_pair()
    c1 = RemotePageStore(server.endpoint, timeout_s=10.0, wire="v1")
    try:
        old, new = _planes(seed=7), _planes(seed=8)
        assert c1.put(("via-v1",), old)
        assert c2.put(("via-v2",), new)
        assert _bits(c2.get(("via-v1",))) == _bits(old)
        assert _bits(c1.get(("via-v2",))) == _bits(new)
        # v1's loop-based batched fallbacks match v2's single frame.
        keys = [("via-v1",), ("via-v2",)]
        assert c1.run_len(keys) == c2.run_len(keys) == 2
        assert [_bits(p) for p in c1.get_run(keys)] == [
            _bits(p) for p in c2.get_run(keys)
        ]
    finally:
        c1.close()
        c2.close()
        server.close()


def test_batched_ops_semantics():
    """put_many/get_run/touch_many/run_len in ONE round trip keep the
    per-key contracts: runs stop at the first miss (chain keys are
    prefix-nested), touches report per-key residency."""
    store, server, client = _live_pair()
    try:
        items = [(("c", i), _planes(seed=i)) for i in range(4)]
        out = client.put_many(items)
        assert out == [(True, 1, 0)] * 4
        # A hole after key 1: the run and its probe stop there.
        probe = [("c", 0), ("c", 1), ("hole",), ("c", 3)]
        assert client.run_len(probe) == 2
        run = client.get_run(probe)
        assert len(run) == 2
        assert _bits(run[1]) == _bits(items[1][1])
        assert client.touch_many(probe) == [True, True, False, True]
        assert client.get_run([]) == [] and client.run_len([]) == 0
    finally:
        client.close()
        server.close()


def test_pipelined_concurrent_ops_bit_exact():
    """Many threads share ONE v2 connection: replies dispatch to their
    waiters by sequence tag, every round trip bit-exact, zero errors."""
    store, server, client = _live_pair()
    failures = []

    def worker(t):
        try:
            for i in range(8):
                key = ("t", t, i)
                planes = _planes(seed=t * 100 + i)
                assert client.put(key, planes)
                got = client.get(key)
                assert got is not None and _bits(got) == _bits(planes)
        except Exception as e:  # noqa: BLE001 - collected for the assert
            failures.append(repr(e))

    try:
        threads = [
            threading.Thread(target=worker, args=(t,)) for t in range(4)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        assert not failures
        assert client.errors == 0
        assert len(store) == 32
    finally:
        client.close()
        server.close()


def test_transfer_bytes_family_lockstep():
    """Client tx/rx mirrors count exactly the plane payload bytes that
    crossed the wire, and the process-global
    ``gateway_transfer_bytes_total`` family moves by the same deltas."""
    store, server, client = _live_pair()
    try:
        tx0 = TRANSFER_BYTES.labels(dir="tx").value
        rx0 = TRANSFER_BYTES.labels(dir="rx").value
        ctx0, crx0 = client.tx_bytes, client.rx_bytes
        planes = _planes(seed=3)
        nbytes = sum(int(p.nbytes) for p in planes)
        assert client.put(("xfer",), planes)
        assert client.tx_bytes - ctx0 == nbytes
        assert client.rx_bytes == crx0  # put replies carry no planes
        assert client.get(("xfer",)) is not None
        assert client.rx_bytes - crx0 == nbytes
        assert TRANSFER_BYTES.labels(dir="tx").value - tx0 == nbytes
        assert TRANSFER_BYTES.labels(dir="rx").value - rx0 == nbytes
        # Planeless ops move nothing.
        client.refresh_stats()
        assert ("xfer",) in client
        assert client.tx_bytes - ctx0 == nbytes
        assert client.rx_bytes - crx0 == nbytes
    finally:
        client.close()
        server.close()


# ---------------------------------------------------------------------------
# Transport robustness: fuzzing, backpressure, NODELAY, mid-stream kill
# ---------------------------------------------------------------------------


def test_fuzzed_frames_drop_one_connection_only():
    """Bogus magic, oversized preludes, truncated bodies, and garbage
    v1 pickles each cost ONE connection; the listener and a well-formed
    client keep working after every one of them."""
    store, server, client = _live_pair()
    _, addr = parse_endpoint(server.endpoint)
    good = _planes(seed=9)
    assert client.put(("good",), good)

    def poke(raw: bytes):
        s = socket.create_connection(addr, timeout=5)
        try:
            s.sendall(raw)
            s.settimeout(2)
            try:
                while s.recv(4096):
                    pass  # drain until the server hangs up
            except (socket.timeout, OSError):
                pass  # a reset IS the hang-up
        finally:
            s.close()

    try:
        # Not v2 magic -> sniffed as a v1 length prefix of ~1.4 GiB:
        # past _MAX_FRAME, refused without allocation.
        poke(b"ZZZZ" + b"\x00" * 16)
        # v2 prelude claiming a header past the frame cap.
        poke(_PRELUDE.pack(_MAGIC, 2, 1, 1 << 30, 0))
        # v2 prelude with a plausible size but a truncated body.
        poke(_PRELUDE.pack(_MAGIC, 2, 2, 64, 4096) + b"\x01" * 10)
        # Valid v1 length prefix framing unpicklable bytes.
        poke(_LEN.pack(20) + b"\xde\xad\xbe\xef" * 5)
        # The server survived all four: same client, same connection
        # pool, bit-exact reads and fresh writes still work.
        assert _bits(client.get(("good",))) == _bits(good)
        assert client.put(("after",), _planes(seed=10))
        assert client.errors == 0
    finally:
        client.close()
        server.close()


def test_send_vec_backpressure_and_chunking():
    """_send_vec against a slow consumer with a tiny send buffer: the
    partial-send resume logic and >_IOV_MAX chunking both hit, and the
    byte stream arrives intact and ordered."""
    a, b = socket.socketpair()
    a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
    # 600 small views (> _IOV_MAX forces at least two sendmsg chunks)
    # plus two bulk planes to force partial sends at the buffer size.
    rng = np.random.default_rng(11)
    views = [rng.integers(0, 256, 37, dtype=np.uint8) for _ in range(600)]
    views += [rng.integers(0, 256, 256 << 10, dtype=np.uint8) for _ in range(2)]
    assert len(views) > _IOV_MAX
    want = hashlib.sha256()
    total = 0
    for v in views:
        want.update(v.tobytes())
        total += v.nbytes

    got = hashlib.sha256()
    received = 0

    def consume():
        nonlocal received
        while received < total:
            chunk = b.recv(8192)
            if not chunk:
                break
            got.update(chunk)
            received += len(chunk)
            time.sleep(0.001)  # slow consumer: keep the sender blocked

    t = threading.Thread(target=consume)
    t.start()
    try:
        _send_vec(a, [memoryview(v) for v in views])
    finally:
        a.close()
        t.join(timeout=60)
        b.close()
    assert received == total
    assert got.digest() == want.digest()


def test_tcp_nodelay_set_on_both_ends():
    store, server, client = _live_pair()
    try:
        assert client.put(("nd",), _planes(seed=12))
        opt = client._sock.getsockopt(
            socket.IPPROTO_TCP, socket.TCP_NODELAY
        )
        assert opt != 0
        with server._conns_lock:
            conns = list(server._conns)
        assert conns
        for c in conns:
            assert c.getsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY) != 0
    finally:
        client.close()
        server.close()


def test_killed_server_fails_pipeline_to_misses():
    """A hard server kill mid-pipeline: every in-flight and subsequent
    op degrades to a miss within the op timeout, errors are counted,
    the circuit opens, and nothing wedges."""
    store, server, client = _live_pair()
    client.timeout_s = 1.0
    client.retry_s = 30.0  # keep the circuit open for the test's tail
    assert client.put(("pre",), _planes(seed=13))
    results = []

    def hammer():
        for _ in range(10):
            results.append(client.get(("pre",)))

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    server.close()  # hard kill: live conns shut down mid-stream
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    # Post-kill ops are fast misses through the open circuit.
    t0 = time.monotonic()
    assert client.get(("pre",)) is None
    assert not client.put(("post",), _planes(seed=14))
    assert time.monotonic() - t0 < 1.0
    assert client.errors >= 1
    assert None in results  # at least the tail of the hammer missed
    client.close()


# ---------------------------------------------------------------------------
# Serving layer: streamed export, prefetch, handoff-latency lockstep
# ---------------------------------------------------------------------------


def test_streamed_export_spills_and_respects_deadline(params):
    """A streaming export issued WHILE the chain prefills spills every
    usable page and sets its event; one for a chain that never lands
    sets its event at the deadline instead of hanging."""
    store = HostPageStore(budget_bytes=64 << 20)
    b = ContinuousBatcher(
        CFG, params, config=ContinuousConfig(**_SCFG), host_store=store
    )
    try:
        prompt = _HEADER + "s0"
        ids = b.tokenizer.encode(prompt)
        expected = (len(ids) - 1) // _SCFG["page_size"]
        assert expected >= 3
        fut = b.submit(prompt, max_new_tokens=4, temperature=0.0)
        ev = b.request_export(ids, stream_until=time.monotonic() + 20.0)
        assert fut.result(timeout=120).text
        assert ev.wait(20.0)
        assert len(store) >= expected
        assert b.stats()["exported_pages"] >= expected
        # Unknown chain: nothing ever flips ready; the re-arming export
        # gives up at its deadline and STILL sets the event.
        ghost = b.tokenizer.encode(_HEADER + "never-submitted")
        ev2 = b.request_export(ghost, stream_until=time.monotonic() + 0.4)
        assert ev2.wait(5.0)
        assert len(store) >= expected  # the ghost spilled nothing new
    finally:
        b.close()


def test_prefetch_staged_hit_and_cold_fallthrough(params):
    """Route-driven prefetch end to end: a warm store's chain stages
    ahead of admission and is consumed as restore-plan hits (metrics
    in lockstep with the stats mirrors); the same prefetch against a
    COLD store stages nothing and admission recomputes — text
    byte-identical in both worlds."""
    prompt = _HEADER + "pf"
    store = HostPageStore(budget_bytes=64 << 20)

    # Seed the store (and the reference text) from a donor batcher.
    b0 = ContinuousBatcher(
        CFG, params, config=ContinuousConfig(**_SCFG), host_store=store
    )
    try:
        want = _serve(b0, [prompt], max_new_tokens=4, temperature=0.0)[0]
        ids = b0.tokenizer.encode(prompt)
        ev = b0.request_export(ids)
        assert ev.wait(20.0)
        assert len(store) >= 3
    finally:
        b0.close()

    # Warm world: prefetch stages the chain, admission consumes it.
    f0 = KV_PREFETCH.labels(event="fetched").value
    h0 = KV_PREFETCH.labels(event="hit").value
    b1 = ContinuousBatcher(
        CFG, params, config=ContinuousConfig(**_SCFG), host_store=store
    )
    try:
        assert b1.prefetch_chain(ids) is True
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if b1.stats()["prefetch_fetched_pages"] >= 3:
                break
            time.sleep(0.02)
        s = b1.stats()
        assert s["prefetch_fetched_pages"] >= 3
        assert s["prefetch_staged_pages"] >= 3
        got_warm = _serve(b1, [prompt], max_new_tokens=4, temperature=0.0)[0]
        s = b1.stats()
        assert s["prefetch_hit_pages"] >= 1
        # Prometheus family deltas match the batcher's stats mirrors.
        assert (
            KV_PREFETCH.labels(event="fetched").value - f0
            == s["prefetch_fetched_pages"]
        )
        assert (
            KV_PREFETCH.labels(event="hit").value - h0
            == s["prefetch_hit_pages"]
        )
    finally:
        b1.close()
    assert got_warm == want

    # Cold world: the prefetch guess finds nothing; admission falls
    # through to recompute. Never corrupts, never blocks — and the
    # text is still byte-identical.
    b2 = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(**_SCFG),
        host_store=HostPageStore(budget_bytes=64 << 20),
    )
    try:
        assert b2.prefetch_chain(ids) is True
        time.sleep(0.3)  # let the guess run against the empty store
        got_cold = _serve(b2, [prompt], max_new_tokens=4, temperature=0.0)[0]
        s = b2.stats()
        assert s["prefetch_fetched_pages"] == 0
        assert s["prefetch_hit_pages"] == 0
        assert s["prefetch_staged_pages"] == 0
    finally:
        b2.close()
    assert got_cold == want


def test_handoff_seconds_lockstep_on_roled_fleet(params):
    """gateway_handoff_seconds moves in lockstep with the fleet's
    handoff_seconds_sum/count mirrors, and the streamed handoff's
    flight events say so."""
    hc0, hs0 = HANDOFF_SECONDS.count, HANDOFF_SECONDS.sum
    fleet = ReplicaSet(
        CFG,
        params,
        config=ContinuousConfig(**_SCFG),
        fleet=FleetConfig(replicas=2, role=("prefill", "decode")),
        host_store=HostPageStore(budget_bytes=64 << 20),
    )
    try:
        texts = _serve(
            fleet,
            [f"{_HEADER}h{i}?" for i in range(3)],
            max_new_tokens=4,
            temperature=0.0,
        )
        assert all(texts)
        stats = fleet.stats()
    finally:
        fleet.close()
    assert stats["role_handoffs"] >= 1
    assert stats["handoff_seconds_count"] == stats["role_handoffs"]
    assert HANDOFF_SECONDS.count - hc0 == stats["handoff_seconds_count"]
    assert HANDOFF_SECONDS.sum - hs0 == pytest.approx(
        stats["handoff_seconds_sum"]
    )
    assert stats["handoff_seconds_sum"] > 0.0
    streamed = [
        e.meta.get("streamed")
        for e in flight.flight_recorder().events()
        if e.kind == "handoff"
    ]
    assert streamed and streamed[-1] is True  # handoff_stream default on
