"""Mesh-native serving hot path (PR 13, serving/continuous.py +
models/transformer.py + ops/pallas/attention.py).

The contract: a dp2×mp2 mesh batcher serves BYTE-IDENTICAL text to the
single-device batcher with every serving feature engaged — fused ragged
dispatch, grouped prefix attention, multi-round decode, speculative
decoding, and the host KV tier all lost their mesh fallbacks. The
parity grid sweeps {ragged on/off} × {decode_rounds 1,4} × {spec
on/off} × {pipeline_depth 1,2}; the kernel-level test drives the
shard_map'd Pallas ragged program against the XLA reference; the cost
model's multi-round accounting (R rounds of KV reads, ONE weight read
per program) is pinned on and off mesh.
"""

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import (
    init_params,
    kv_plane_token_bytes,
    model_param_bytes,
    ragged_mesh_shardable,
)
from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)

CFG = get_config("test-tiny")

# Panel-shaped burst: two shared headers (prefix groups + shared draft
# streams form) plus unique prompts (ungrouped rows coexist). Headers
# are EXACTLY 2 pages at page_size 16 and the questions diverge at
# their first token, so the share plan is deterministic — 2 full pages
# mapped, no boundary-page candidate whose readiness (a race against
# the donor's prefill) could flip the plan between shared and
# unshared across runs.
_HEADER_A = "shared mesh panel header alpha!!"  # 32 chars = 2 pages
_HEADER_B = "other shared panel header beta!!"
assert len(_HEADER_A) == len(_HEADER_B) == 32
PROMPTS = [
    _HEADER_A + "one?",
    _HEADER_A + "two?",
    "a unique short prompt",
    _HEADER_B + "three?",
    _HEADER_B + "four?",
    "another unique tail prompt?",
]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _mesh22():
    return make_mesh(MeshConfig(data=2, model=2), devices=jax.devices()[:4])


def _ccfg(**kw):
    base = dict(
        max_slots=4,
        page_size=16,
        n_pages=64,
        pages_per_seq=8,
        max_new_tokens=8,
        seq_buckets=(16, 32, 64),
    )
    base.update(kw)
    return ContinuousConfig(**base)


def _quiesce(b, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = b.stats()
        if (
            not s["dispatch_inflight"]
            and not s["active_slots"]
            and not s["prefilling_slots"]
            and not s["waiting"]
        ):
            return s
        time.sleep(0.02)
    raise AssertionError("batcher did not quiesce")


def _serve(params, mesh=None, draft=False, prompts=PROMPTS, **kw):
    b = ContinuousBatcher(
        CFG,
        params,
        config=_ccfg(**kw),
        mesh=mesh,
        draft=(CFG, params) if draft else None,
    )
    try:
        futs = [b.submit(p) for p in prompts]
        texts = [f.result(timeout=300).text for f in futs]
        stats = _quiesce(b)
    finally:
        b.close()
    return texts, stats


@pytest.fixture(scope="module")
def reference(params):
    """Single-device default-config texts — the byte-parity oracle for
    every grid cell (ragged/rounds/spec/depth are all byte-invariant
    contracts, so one reference covers the whole grid)."""
    texts, _ = _serve(params)
    return texts


# ---------------------------------------------------------------------------
# Parity grid: dp2×mp2 vs single device, byte-identical text
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("depth", [1, 2])
@pytest.mark.parametrize("rounds", [1, 4])
@pytest.mark.parametrize("ragged", [False, True])
def test_mesh_parity_grid_plain(params, reference, ragged, rounds, depth):
    texts, stats = _serve(
        params,
        mesh=_mesh22(),
        ragged_attention=ragged,
        decode_rounds=rounds,
        pipeline_depth=depth,
    )
    assert texts == reference
    assert stats["mesh_data_shards"] == 2
    assert stats["mesh_model_shards"] == 2
    if rounds == 4:
        # Multi-round decode really engaged on the mesh: at least one
        # dispatched program held more than one decode round.
        assert stats["decode_rounds_sum"] > stats["decode_rounds_count"]
    if ragged:
        assert stats["device_programs_fused"] > 0


@pytest.mark.parametrize("depth", [1, 2])
@pytest.mark.parametrize("rounds", [1, 4])
@pytest.mark.parametrize("ragged", [False, True])
def test_mesh_parity_grid_spec(params, reference, ragged, rounds, depth):
    """Speculative decoding on the mesh (self-draft: acceptance ~1.0,
    text must equal plain greedy for ANY draft). decode_rounds rides
    along: spec windows stay one verify round per dispatch, so the R
    cells prove composition, not R-round programs."""
    texts, stats = _serve(
        params,
        mesh=_mesh22(),
        draft=True,
        spec_k=2,
        ragged_attention=ragged,
        decode_rounds=rounds,
        pipeline_depth=depth,
    )
    assert texts == reference
    assert stats["device_programs_spec"] > 0
    assert stats["spec_accepted_tokens"] > 0


# ---------------------------------------------------------------------------
# Engagement: no fallback warnings, one program per iteration
# ---------------------------------------------------------------------------


def test_mesh_full_config_constructs_without_fallback_warnings(
    params, caplog
):
    """The acceptance criterion's warning half: a dp2×mp2 batcher with
    EVERY feature configured (ragged fusion, R=4, spec draft, host
    tier) must not emit any engage-fallback warning — the old blanket
    spec-on-mesh / rounds-on-mesh warnings are gone, and nothing else
    fires for this config."""
    with caplog.at_level(
        logging.WARNING, logger="llm_consensus_tpu.serving.continuous"
    ):
        b = ContinuousBatcher(
            CFG,
            params,
            config=_ccfg(
                decode_rounds=4, spec_k=2, host_cache_bytes=32 << 20
            ),
            mesh=_mesh22(),
            draft=(CFG, params),
        )
        try:
            assert b._fused_ok
            assert b._rounds == 4 or b._spec_ok  # spec wins the window
            assert b._spec_ok
            assert b._offload is not None
        finally:
            b.close()
    assert not [r for r in caplog.records if r.levelno >= logging.WARNING]


def test_mesh_fused_one_program_per_iteration(params):
    """The bench gate's substance: on the mesh with fusion on (spec
    off so chunks ride decode dispatches), the mixed burst runs EXACTLY
    one device program per scheduler work iteration."""
    b = ContinuousBatcher(CFG, params, config=_ccfg(), mesh=_mesh22())
    try:
        # Warm one request through so compile time doesn't stretch the
        # measured burst (the ratio is count-based, but keep the burst
        # representative).
        b.submit(_HEADER_A + "warm").result(timeout=300)
        _quiesce(b)
        s0 = b.stats()
        futs = [b.submit(p) for p in PROMPTS]
        [f.result(timeout=300) for f in futs]
        s1 = _quiesce(b)
    finally:
        b.close()
    programs = sum(
        s1[k] - s0[k]
        for k in (
            "device_programs_fused",
            "device_programs_decode",
            "device_programs_prefill",
        )
    )
    iters = s1["work_iterations"] - s0["work_iterations"]
    assert iters > 0
    assert programs == iters  # ratio == 1.0 exactly
    assert s1["device_programs_fused"] > s0["device_programs_fused"]


def test_mesh_grouped_prefix_attention_engages_with_pallas(params):
    """Grouped prefix attention on the mesh: a use_pallas config (the
    kernel runs interpreted on CPU, shard_map'd over dp2×mp2) forms
    groups, counts shared-KV savings, and still serves the exact text
    of the single-device Pallas batcher."""
    cfg = CFG.with_(use_pallas=True)
    assert ragged_mesh_shardable(cfg, _mesh22(), 4, 64)
    prompts = PROMPTS[:4]

    def run(mesh):
        b = ContinuousBatcher(cfg, params, config=_ccfg(), mesh=mesh)
        try:
            futs = [b.submit(p) for p in prompts]
            texts = [f.result(timeout=600).text for f in futs]
            stats = _quiesce(b)
        finally:
            b.close()
        return texts, stats

    want, s_one = run(None)
    got, s_mesh = run(_mesh22())
    assert got == want
    assert s_one["shared_kv_bytes_saved"] > 0
    # Groups form per data shard (pages never share across shards);
    # with the panel split over two shards the savings shrink but the
    # grouped read really runs on the mesh.
    assert s_mesh["shared_kv_bytes_saved"] > 0
    assert s_mesh["decode_group_peak"] >= 2


# ---------------------------------------------------------------------------
# Sharded ragged kernel vs XLA reference (kernel level)
# ---------------------------------------------------------------------------


def test_sharded_ragged_kernel_matches_reference():
    """shard_map'd Pallas ragged program (heads over model, rows/pages
    over data, rebased tables) vs the ungrouped XLA reference: decode
    rows, the chunk lane on its owner shard, shard-local groups, the
    sliding window, and the NQ-query verify lane."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from llm_consensus_tpu.ops.attention import (
        ragged_paged_attention_reference,
    )
    from llm_consensus_tpu.ops.pallas.attention import (
        ragged_paged_attention_sharded,
    )

    mesh = _mesh22()
    key = jax.random.PRNGKey(0)
    b, h, hkv, d = 4, 4, 2, 128
    n_pages, pg, p_per = 16, 8, 4  # 8 pages per data shard
    ks = jax.random.split(key, 8)
    q = jax.random.normal(ks[0], (b, h, d), jnp.float32)
    k_pool = jax.random.normal(ks[1], (n_pages, pg, hkv, d), jnp.float32)
    v_pool = jax.random.normal(ks[2], (n_pages, pg, hkv, d), jnp.float32)
    # Rows 0,1 draw pages from shard 0's range [0, 8); rows 2,3 from
    # shard 1's [8, 16) — the allocator's slot→shard affinity. Rows
    # 0/1 share page 1, rows 2/3 share page 8 (two shard-local
    # groups). Every row's valid length stays within its mapped pages
    # (the serving invariant the rebase clamp relies on).
    table = np.zeros((b, p_per), np.int32)
    table[0] = [1, 2, 3, 0]
    table[1] = [1, 4, 0, 0]
    table[2] = [8, 9, 0, 0]
    table[3] = [8, 10, 11, 0]
    valid = np.asarray([22, 13, 11, 23], np.int32)
    gid = np.asarray([0, 0, 1, 1], np.int32)
    rep = np.asarray([0, 2], np.int32)
    gend = np.asarray([8, 8], np.int32)
    sstart = np.asarray([8, 8, 8, 8], np.int32)
    cq = 4
    q_chunk = jax.random.normal(ks[3], (cq, h, d), jnp.float32)
    chunk_table = np.zeros((p_per,), np.int32)
    chunk_table[:2] = [12, 13]  # owner: shard 1
    chunk_start = jnp.int32(8)

    pool_sh = NamedSharding(mesh, P("data", None, "model", None))
    sq = jax.device_put(q, NamedSharding(mesh, P("data", "model", None)))
    skp = jax.device_put(k_pool, pool_sh)
    svp = jax.device_put(v_pool, pool_sh)
    stab = jax.device_put(
        jnp.asarray(table), NamedSharding(mesh, P("data", None))
    )
    sval = jax.device_put(
        jnp.asarray(valid), NamedSharding(mesh, P("data"))
    )

    ref_dec, ref_ch = ragged_paged_attention_reference(
        q, k_pool, v_pool, jnp.asarray(table), jnp.asarray(valid),
        q_chunk=q_chunk, chunk_table=jnp.asarray(chunk_table),
        chunk_start=chunk_start,
    )
    out_dec, out_ch = jax.jit(
        lambda *a: ragged_paged_attention_sharded(
            mesh, *a,
            q_chunk=q_chunk, chunk_table=jnp.asarray(chunk_table),
            chunk_start=chunk_start,
            groups=(
                jnp.asarray(gid), jnp.asarray(rep),
                jnp.asarray(gend), jnp.asarray(sstart),
            ),
        )
    )(sq, skp, svp, stab, sval)
    np.testing.assert_allclose(
        np.asarray(out_dec), np.asarray(ref_dec), atol=2e-5, rtol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(out_ch), np.asarray(ref_ch), atol=2e-5, rtol=2e-5
    )

    # Sliding window, no groups/chunk.
    ref_w = ragged_paged_attention_reference(
        q, k_pool, v_pool, jnp.asarray(table), jnp.asarray(valid), window=9
    )
    out_w = jax.jit(
        lambda *a: ragged_paged_attention_sharded(mesh, *a, window=9)
    )(sq, skp, svp, stab, sval)
    np.testing.assert_allclose(
        np.asarray(out_w), np.asarray(ref_w), atol=2e-5, rtol=2e-5
    )

    # NQ-query verify lane (the spec path's attention shape).
    nq = 3
    qv = jax.random.normal(ks[4], (b, nq, h, d), jnp.float32)
    sqv = jax.device_put(
        qv, NamedSharding(mesh, P("data", None, "model", None))
    )
    ref_v = ragged_paged_attention_reference(
        qv, k_pool, v_pool, jnp.asarray(table), jnp.asarray(valid)
    )
    out_v = jax.jit(
        lambda *a: ragged_paged_attention_sharded(mesh, *a)
    )(sqv, skp, svp, stab, sval)
    np.testing.assert_allclose(
        np.asarray(out_v), np.asarray(ref_v), atol=2e-5, rtol=2e-5
    )


def test_ragged_mesh_shardable_predicate(params, caplog):
    mesh = _mesh22()
    assert ragged_mesh_shardable(CFG, mesh, 4, 64)  # Hkv=2 % mp=2 == 0
    draft = get_config("test-tiny-draft")
    assert not ragged_mesh_shardable(draft, mesh, 4, 64)  # Hkv=1 % 2
    assert not ragged_mesh_shardable(CFG, mesh, 3, 64)  # slots % dp
    assert not ragged_mesh_shardable(CFG, None, 4, 64)
    # A non-shardable use_pallas mesh config: the remaining-reason
    # warning fires once, and grouped decode stays OFF (the reference
    # fallback ignores groups — telemetry must not claim savings the
    # program doesn't perform).
    dparams = init_params(draft, jax.random.PRNGKey(0), dtype=jnp.float32)
    with caplog.at_level(
        logging.WARNING, logger="llm_consensus_tpu.serving.continuous"
    ):
        b = ContinuousBatcher(
            draft.with_(use_pallas=True), dparams, config=_ccfg(),
            mesh=mesh,
        )
        try:
            assert not b._group_decode
        finally:
            b.close()
    assert any(
        "cannot shard over this mesh" in r.message for r in caplog.records
    )


# ---------------------------------------------------------------------------
# Host tier on the mesh: demote → restore bit identity
# ---------------------------------------------------------------------------


def test_mesh_host_tier_demote_restore_bit_identity(params):
    """The PR-4 round-trip contract on SHARDED planes: the demote
    device_get assembles the page's shard slices, the restore
    install_page scatters them back through the pool's NamedSharding,
    and the restored device page holds exactly the fresh prefill's
    bytes — text unchanged across the eviction."""
    from llm_consensus_tpu.serving.offload import page_planes

    header = "mesh offload header payload " * 3  # > 1 full page
    b = ContinuousBatcher(
        CFG,
        params,
        config=_ccfg(n_pages=32, max_new_tokens=6,
                     host_cache_bytes=64 << 20),
        mesh=_mesh22(),
    )
    try:
        t1 = b.submit(header + "Q?").result(timeout=300).text
        _quiesce(b)
        # Fresh-prefill bytes of the header's first full page (the
        # admitting slot's shard registry holds the chain). The
        # registry keys on the ADMITTED ids — the prompt left-truncates
        # to the largest bucket.
        ids = b.tokenizer.encode(header + "Q?")[-64:]
        key0 = tuple(int(t) for t in ids[:16])
        reg = next(
            r for r in b._registries if key0 in r._root.children
        )
        node0 = reg._root.children[key0]
        fresh = page_planes(b.cache, node0.page)
        # Filler storm starves the pool → the header's registry pages
        # demote to the host tier.
        fills = [
            b.submit(f"filler {i} " * 6 + "?") for i in range(10)
        ]
        [f.result(timeout=300) for f in fills]
        t2 = b.submit(header + "Q?").result(timeout=300).text
        _quiesce(b)
        s = b.stats()
        node1 = reg._root.children[key0]
        restored = page_planes(b.cache, node1.page)
    finally:
        b.close()
    assert t1 == t2
    assert s["offload_demoted_pages"] > 0
    assert s["offload_restored_pages"] > 0
    for a, bb in zip(fresh, restored):
        assert a.tobytes() == bb.tobytes()


# ---------------------------------------------------------------------------
# Cost model at R > 1 (PR-12 residual): R rounds of KV reads, ONE
# weight read per program — on and off mesh
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("on_mesh", [False, True])
def test_rounds_cost_model_lockstep(params, on_mesh):
    """gateway_program_mbu{decode}'s inputs at R=4 vs R=1: the SAME
    generation reads the SAME KV token total (sum over steps of L+j is
    window-invariant) and writes the same count, while the weight term
    is counted once per PROGRAM — so hbm bytes drop by exactly
    (programs_R1 - programs_R4) × weight_bytes. max_new_tokens chosen
    so 8 decoded tokens split into exact windows (no early-exit slack
    inflating the planned R)."""
    weight_bytes, _ = model_param_bytes(params)
    kv_tok = kv_plane_token_bytes(CFG, jnp.bfloat16)
    mesh = _mesh22() if on_mesh else None
    prompt = ["one lone cost-model request?"]

    def run(rounds):
        _, s = _serve(
            params,
            mesh=mesh,
            prompts=prompt,
            pipeline_depth=1,
            max_new_tokens=9,  # 1 prefill-sampled + 8 decoded = 2×R4
            decode_rounds=rounds,
        )
        return s

    s1, s4 = run(1), run(4)
    assert s1["device_programs_decode"] == 8
    assert s4["device_programs_decode"] == 2
    assert s4["device_rounds_total"] == 8
    # KV totals are window-invariant; the weight term is per program.
    assert (
        s1["mbu_kv_read_tokens_decode"] == s4["mbu_kv_read_tokens_decode"]
    )
    assert (
        s1["mbu_kv_write_tokens_decode"]
        == s4["mbu_kv_write_tokens_decode"]
        == 8
    )
    for s in (s1, s4):
        kv_bytes = (
            s["mbu_kv_read_tokens_decode"] + s["mbu_kv_write_tokens_decode"]
        ) * kv_tok
        assert (
            s["mbu_hbm_bytes_decode"] - kv_bytes
            == s["device_programs_decode"] * weight_bytes
        )


# ---------------------------------------------------------------------------
# Metrics lockstep for the new family
# ---------------------------------------------------------------------------


def test_bench_serve_mesh_cpu_ab_leg():
    """The CPU-run --serve-mesh A/B leg (acceptance): the mixed panel
    burst on a dp2×mp2 mesh vs single device, byte-identical text per
    pair, mesh-leg programs/iteration == 1.0, rc 0, explicit status in
    the JSON line. (The leg sets
    xla_force_host_platform_device_count itself when the environment
    hasn't — here the harness's exported XLA_FLAGS ride along.)"""
    import subprocess
    import sys
    from pathlib import Path

    r = subprocess.run(
        [
            sys.executable, "bench.py", "--tiny", "--cpu",
            "--serve-mesh", "--serve-requests", "6",
            "--serve-slots", "4", "--new-tokens", "16",
            "--prompt-len", "32", "--mesh-ab-rounds", "1",
        ],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "mesh-native hot path" in r.stdout
    assert "text equal=True" in r.stdout
    assert "programs/iteration 1.00" in r.stdout
    assert '"status": "ok"' in r.stdout


def test_mesh_shards_gauge_lockstep(params):
    from llm_consensus_tpu.server.metrics import MESH_SHARDS

    b = ContinuousBatcher(CFG, params, config=_ccfg(), mesh=_mesh22())
    try:
        s = b.stats()
        assert s["mesh_data_shards"] == 2
        assert s["mesh_model_shards"] == 2
        assert MESH_SHARDS.labels(axis="data").value == 2
        assert MESH_SHARDS.labels(axis="model").value == 2
    finally:
        b.close()
    b = ContinuousBatcher(CFG, params, config=_ccfg())
    try:
        s = b.stats()
        assert s["mesh_data_shards"] == 1
        assert s["mesh_model_shards"] == 1
        assert MESH_SHARDS.labels(axis="data").value == 1
    finally:
        b.close()
