"""Model-level tests: shapes, causality, and the prefill/decode-vs-full
consistency invariant that validates the whole KV-cache path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models import (
    KVCache,
    forward,
    get_config,
    init_params,
    param_count,
    prefill,
    decode_step,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return cfg, params


@pytest.fixture(scope="module")
def tiny_moe():
    cfg = get_config("test-tiny-moe")
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    return cfg, params


def test_forward_shapes_and_dtype(tiny):
    cfg, params = tiny
    tokens = jnp.arange(12).reshape(2, 6) % cfg.vocab_size
    logits = forward(cfg, params, tokens)
    assert logits.shape == (2, 6, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_forward_is_causal(tiny):
    cfg, params = tiny
    t1 = jnp.array([[1, 2, 3, 4, 5]])
    t2 = jnp.array([[1, 2, 3, 9, 9]])  # change suffix only
    l1 = forward(cfg, params, t1)
    l2 = forward(cfg, params, t2)
    np.testing.assert_allclose(
        np.asarray(l1[:, :3]), np.asarray(l2[:, :3]), rtol=1e-5, atol=1e-5
    )


def test_prefill_then_decode_matches_full_forward(tiny):
    """The load-bearing invariant: incremental decoding through the KV cache
    reproduces the full causal forward exactly (same params, same tokens)."""
    cfg, params = tiny
    b, s_prompt, s_total, max_len = 2, 4, 9, 16
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (b, s_total), 0, cfg.vocab_size)

    full_logits = forward(cfg, params, tokens)  # [B, S_total, V]

    cache = KVCache.create(cfg, b, max_len, dtype=jnp.float32)
    lengths = jnp.full((b,), s_prompt, jnp.int32)
    logits_p, cache = prefill(cfg, params, tokens[:, :s_prompt], lengths, cache)
    np.testing.assert_allclose(
        np.asarray(logits_p),
        np.asarray(full_logits[:, s_prompt - 1]),
        rtol=2e-4,
        atol=2e-4,
    )

    for t in range(s_prompt, s_total):
        logits_d, cache = decode_step(cfg, params, tokens[:, t : t + 1], cache)
        np.testing.assert_allclose(
            np.asarray(logits_d),
            np.asarray(full_logits[:, t]),
            rtol=2e-3,
            atol=2e-3,
        )
    assert int(cache.length[0]) == s_total


def test_prefill_ragged_lengths(tiny):
    """Right-padded prompts of different lengths: each sequence's last-token
    logits must match an unpadded single-sequence run."""
    cfg, params = tiny
    max_len = 16
    t_a = jnp.array([[5, 6, 7]])
    t_b = jnp.array([[8, 9, 10, 11, 12]])
    batch = jnp.zeros((2, 5), jnp.int32)
    batch = batch.at[0, :3].set(t_a[0]).at[1, :5].set(t_b[0])
    lengths = jnp.array([3, 5], jnp.int32)

    cache = KVCache.create(cfg, 2, max_len, dtype=jnp.float32)
    logits, cache = prefill(cfg, params, batch, lengths, cache)

    la = forward(cfg, params, t_a)[:, -1]
    lb = forward(cfg, params, t_b)[:, -1]
    np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(la[0]), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(logits[1]), np.asarray(lb[0]), rtol=2e-4, atol=2e-4)

    # Decode one step for both; compare against per-sequence full forward.
    nxt = jnp.array([[1], [2]])
    logits_d, cache = decode_step(cfg, params, nxt, cache)
    fa = forward(cfg, params, jnp.concatenate([t_a, nxt[:1]], axis=1))[:, -1]
    fb = forward(cfg, params, jnp.concatenate([t_b, nxt[1:]], axis=1))[:, -1]
    np.testing.assert_allclose(np.asarray(logits_d[0]), np.asarray(fa[0]), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(logits_d[1]), np.asarray(fb[0]), rtol=2e-3, atol=2e-3)


def test_decode_step_jit_stable_shapes(tiny):
    """decode_step must be jit-compilable with a fixed signature (one compile
    for the whole decode loop)."""
    cfg, params = tiny
    cache = KVCache.create(cfg, 2, 16, dtype=jnp.float32)
    lengths = jnp.array([3, 3], jnp.int32)
    tokens = jnp.ones((2, 3), jnp.int32)
    _, cache = prefill(cfg, params, tokens, lengths, cache)

    jitted = jax.jit(lambda p, t, c: decode_step(cfg, p, t, c))
    t = jnp.ones((2, 1), jnp.int32)
    _, cache = jitted(params, t, cache)
    _, cache = jitted(params, t, cache)  # second call hits the cache
    assert int(cache.length[0]) == 5


def test_moe_forward_runs_and_is_causal(tiny_moe):
    cfg, params = tiny_moe
    assert cfg.is_moe
    tokens = jnp.arange(10).reshape(2, 5)
    logits = forward(cfg, params, tokens)
    assert logits.shape == (2, 5, cfg.vocab_size)
    # causality
    t2 = tokens.at[:, -1].add(3)
    l2 = forward(cfg, params, t2)
    np.testing.assert_allclose(
        np.asarray(logits[:, :4]), np.asarray(l2[:, :4]), rtol=1e-5, atol=1e-5
    )


def test_moe_prefill_decode_consistency(tiny_moe):
    cfg, params = tiny_moe
    b, s_prompt, s_total = 1, 3, 6
    tokens = jax.random.randint(jax.random.PRNGKey(3), (b, s_total), 0, cfg.vocab_size)
    full_logits = forward(cfg, params, tokens)
    cache = KVCache.create(cfg, b, 8, dtype=jnp.float32)
    logits_p, cache = prefill(
        cfg, params, tokens[:, :s_prompt], jnp.array([s_prompt]), cache
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(full_logits[:, s_prompt - 1]),
        rtol=2e-3, atol=2e-3,
    )
    for t in range(s_prompt, s_total):
        logits_d, cache = decode_step(cfg, params, tokens[:, t : t + 1], cache)
        np.testing.assert_allclose(
            np.asarray(logits_d), np.asarray(full_logits[:, t]),
            rtol=5e-3, atol=5e-3,
        )


def test_qkv_bias_config_runs():
    cfg = get_config("test-tiny").with_(qkv_bias=True, name="test-tiny-bias")
    params = init_params(cfg, jax.random.PRNGKey(4), dtype=jnp.float32)
    assert "bq" in params["blocks"]
    logits = forward(cfg, params, jnp.ones((1, 4), jnp.int32))
    assert logits.shape == (1, 4, cfg.vocab_size)


def test_tied_embeddings_config_runs():
    cfg = get_config("test-tiny").with_(tie_embeddings=True, name="test-tiny-tied")
    params = init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
    assert "lm_head" not in params
    logits = forward(cfg, params, jnp.ones((1, 4), jnp.int32))
    assert logits.shape == (1, 4, cfg.vocab_size)


def test_param_count_plausible():
    cfg = get_config("llama3-8b")
    # Count without materializing 8B params: shape math only.
    D, H, Hkv, F, V, L = (
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.d_ff,
        cfg.vocab_size,
        cfg.n_layers,
    )
    Dh = cfg.head_dim
    per_layer = (
        2 * D  # norms
        + D * H * Dh
        + 2 * D * Hkv * Dh
        + H * Dh * D
        + 3 * D * F
    )
    total = V * D + L * per_layer + D + D * V
    assert 7.5e9 < total < 8.5e9  # ~8B as advertised

    tiny_cfg = get_config("test-tiny")
    params = init_params(tiny_cfg, jax.random.PRNGKey(0))
    assert param_count(params) > 0


def test_unstacked_blocks_match_stacked():
    """unstack_blocks must not change any output: forward, generate on
    both cache types (the single-chip decode fast path's contract)."""
    import jax
    import jax.numpy as jnp

    from llm_consensus_tpu.engine.generate import generate
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import (
        forward,
        init_params,
        unstack_blocks,
    )

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    flat = unstack_blocks(params)
    assert isinstance(flat["blocks"], tuple) and len(flat["blocks"]) == cfg.n_layers
    assert unstack_blocks(flat) is flat  # idempotent

    # Unrolled layers compile to a differently-fused program, so bf16
    # rounding differs in the last bits — allclose, not bit-equal.
    import numpy as np

    tokens = jnp.array([[5, 9, 13, 17, 2, 0, 0, 0]], jnp.int32)
    f1 = forward(cfg, params, tokens)
    f2 = forward(cfg, flat, tokens)
    np.testing.assert_allclose(f1, f2, rtol=2e-2, atol=2e-2)

    lengths = jnp.array([5], jnp.int32)
    for kv_quant in (False, True):
        g1 = generate(
            cfg, params, tokens, lengths, jax.random.PRNGKey(1),
            jnp.zeros(1), max_new_tokens=6, kv_quant=kv_quant,
        )
        g2 = generate(
            cfg, flat, tokens, lengths, jax.random.PRNGKey(1),
            jnp.zeros(1), max_new_tokens=6, kv_quant=kv_quant,
        )
        assert g1.tokens.tolist() == g2.tokens.tolist()
        np.testing.assert_allclose(
            g1.logprob_sum, g2.logprob_sum, rtol=2e-2, atol=2e-2
        )
