"""Capacity-bounded MoE dispatch (transformer._moe_dispatch).

The dense MoE path computes every expert for every token; the dispatch
path computes only routed tokens within a static capacity. With
capacity_factor = E no token can ever be dropped, so the two paths must
agree exactly — that is the correctness anchor.
"""

import jax
import jax.numpy as jnp

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import forward, init_params
from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh
from llm_consensus_tpu.parallel.partitioning import shard_params

# moe_dense_decode_tokens=0 pins the capacity path: these tests run at
# tiny token counts, below the default trace-time dense-fallback
# threshold (configs.ModelConfig.moe_dense_decode_tokens).
CFG = get_config("test-tiny-moe").with_(moe_dense_decode_tokens=0)


def _setup():
    params = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size
    )
    return params, tokens


def test_dispatch_matches_dense_at_full_capacity():
    params, tokens = _setup()
    ref = forward(CFG, params, tokens)
    out = forward(
        CFG.with_(moe_capacity_factor=float(CFG.n_experts)), params, tokens
    )
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-5


def test_dispatch_bounded_capacity_close_and_finite():
    """cf=1.25 may drop tokens (their expert contribution vanishes) but
    stays finite and close to dense on a well-routed batch."""
    params, tokens = _setup()
    ref = forward(CFG, params, tokens)
    out = forward(CFG.with_(moe_capacity_factor=1.25), params, tokens)
    assert bool(jnp.all(jnp.isfinite(out)))
    # Routing weights are top-2/8 on random init: most tokens fit.
    rel = float(
        jnp.max(jnp.abs(out - ref)) / (jnp.max(jnp.abs(ref)) + 1e-9)
    )
    assert rel < 0.5


def test_dispatch_shards_over_expert_axis(cpu_devices):
    """The dispatch einsums must compile under EP sharding."""
    params, tokens = _setup()
    mesh = make_mesh(MeshConfig(data=2, expert=4), cpu_devices)
    sharded = shard_params(params, mesh)
    cfg = CFG.with_(moe_capacity_factor=2.0)
    out = forward(cfg, sharded, tokens)
    ref = forward(cfg, params, tokens)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-4


def test_dense_fallback_below_token_threshold():
    """Decode-shape auto-switch: with T <= moe_dense_decode_tokens a
    capacity config takes the dense path (no capacity drops), observable
    by starving capacity — cf=0.01 with the dispatch pinned drops nearly
    every token, while the auto-switched output equals plain dense."""
    params, tokens = _setup()  # T = 32 tokens
    dense = forward(CFG.with_(moe_capacity_factor=0.0), params, tokens)
    auto = forward(
        CFG.with_(moe_capacity_factor=0.01, moe_dense_decode_tokens=256),
        params,
        tokens,
    )
    pinned = forward(CFG.with_(moe_capacity_factor=0.01), params, tokens)
    assert float(jnp.max(jnp.abs(auto - dense))) < 1e-6
    assert float(jnp.max(jnp.abs(pinned - dense))) > 1e-3


def test_dispatch_grad_flows():
    """Training through the dispatch path: finite loss and grads."""
    params, tokens = _setup()
    cfg = CFG.with_(moe_capacity_factor=2.0)

    def loss(p):
        lg = forward(cfg, p, tokens)
        return jnp.mean(jax.nn.logsumexp(lg, -1))

    val, grads = jax.value_and_grad(loss)(params)
    assert bool(jnp.isfinite(val))
    flat = jax.tree_util.tree_leaves(grads)
    assert all(bool(jnp.all(jnp.isfinite(g))) for g in flat)
    assert float(jnp.max(jnp.abs(grads["blocks"]["router"]))) > 0
