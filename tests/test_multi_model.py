"""Multi-model consensus serving (PR 18).

Contract layers:

- ALIGNMENT: `serving.vocab_align.align_vocabs` builds exact-match
  remap tables between tokenizers — identity for a shared tokenizer,
  a round-tripping subset map for overlapping vocabs, and a documented
  DISENGAGE (None + warning) below the coverage threshold.
- BATCHER: cross-model speculation through a non-identity map is
  byte-identical to spec-off (the accept rule is exact for any one-hot
  proposal; the remap only moves the acceptance rate), and a real
  cross-model accept is visible in stats, Prometheus, and the flight
  trace. The witness draft is a VOCAB-PERMUTED TWIN — the target's own
  weights with embedding rows / lm_head columns gathered through the
  map — so it proposes the target's argmax chain expressed in a
  different vocab and acceptance is structural, not luck.
- MODELSET: N engines behind one backend — per-model dispatch,
  engage-matrix audit, phase routing for consensus, per-model lanes.
- FLEET: `ReplicaSet` rejects per-replica configs at construction
  (the live-knob-flip contract, satellite fix) and its probe reports
  the model/weights scope (satellite fix).
"""

import asyncio
import logging
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.backends.base import (
    BackendError,
    GenerationRequest,
    SamplingParams,
)
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer, Tokenizer
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)
from llm_consensus_tpu.serving.modelset import (
    ModelSet,
    ModelSetBackend,
    ModelSpec,
)
from llm_consensus_tpu.serving.vocab_align import align_vocabs

CFG = get_config("test-tiny")

_CCFG = dict(
    max_slots=4,
    page_size=16,
    n_pages=96,
    pages_per_seq=10,
    max_new_tokens=10,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
)


class ShiftedByteTokenizer(Tokenizer):
    """Byte tokenizer with a DIFFERENT id layout (byte + 4, id 3 is a
    hole): same text space, shifted vocab — the minimal heterogeneous
    tokenizer for the alignment path. Deliberately not a ByteTokenizer
    subclass: that would take align_vocabs' byte fast path instead of
    the round-trip scan under test."""

    def __init__(self) -> None:
        self.pad_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self._offset = 4
        self.vocab_size = 256 + self._offset

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [
            b + self._offset
            for b in text.encode("utf-8", errors="surrogateescape")
        ]
        return [self.bos_id] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(
            i - self._offset
            for i in ids
            if self._offset <= i < self._offset + 256
        )
        return data.decode("utf-8", errors="surrogateescape")


class WordTokenizer(Tokenizer):
    """Closed word vocab whose every id decodes to a multi-byte string:
    nothing round-trips to a single byte id, so alignment coverage
    against the byte layout collapses to ~0 (the disengage case)."""

    def __init__(self, n: int = 64) -> None:
        self.pad_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self.vocab_size = n

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        return [self.bos_id] if add_bos else []

    def decode(self, ids: Sequence[int]) -> str:
        return " ".join(f"word{i}" for i in ids if i > 2)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _twin_params(params, d2t):
    """Vocab-permuted twin: the target's own network with embedding
    rows and lm_head columns gathered through the draft->target map, so
    the twin computes the target's function expressed in the DRAFT
    vocab. Its greedy chain, remapped, IS the target's — cross-model
    acceptance is then structural rather than random-weight luck."""
    g = jnp.asarray(np.asarray(d2t), jnp.int32)
    twin = dict(params)
    twin["embed"] = params["embed"][g]
    if "lm_head" in params:
        twin["lm_head"] = params["lm_head"][:, g]
    return twin


def _serve(batcher, prompts, **kw):
    futs = [batcher.submit(p, **kw) for p in prompts]
    return [f.result(timeout=180) for f in futs]


# ---------------------------------------------------------------------------
# Alignment grid
# ---------------------------------------------------------------------------


def test_align_identity_for_shared_tokenizer():
    tok = ByteTokenizer()
    m = align_vocabs(tok, tok)
    assert m is not None and m.identity
    assert m.coverage == 1.0
    assert np.array_equal(np.asarray(m.d2t), np.arange(tok.vocab_size))
    assert np.array_equal(np.asarray(m.t2d), np.arange(tok.vocab_size))
    # Two distinct byte tokenizers are the same closed layout.
    m2 = align_vocabs(ByteTokenizer(), ByteTokenizer())
    assert m2 is not None and m2.identity
    assert m.scope_key() == m2.scope_key()


def test_align_overlapping_subset_round_trip():
    target, draft = ByteTokenizer(), ShiftedByteTokenizer()
    m = align_vocabs(target, draft)
    assert m is not None and not m.identity
    assert m.coverage > 0.99  # 256 of 257 scanned (id 3 is the hole)
    d2t, t2d = np.asarray(m.d2t), np.asarray(m.t2d)
    # Specials pinned structurally.
    assert d2t[draft.pad_id] == target.pad_id
    assert d2t[draft.bos_id] == target.bos_id
    assert d2t[draft.eos_id] == target.eos_id
    # Every mapped byte id round-trips: same byte under both layouts,
    # and the inverse view returns to the original id.
    for b in (0, 7, 65, 128, 200, 255):
        did, tid = b + 4, b + 3
        assert d2t[did] == tid
        assert t2d[tid] == did
        assert target.decode([int(d2t[did])]) == draft.decode([did])
    # The hole id stays unmapped (-> target pad).
    assert d2t[3] == target.pad_id


def test_align_below_threshold_disengages_with_warning(caplog):
    with caplog.at_level(logging.WARNING, "llm_consensus_tpu"):
        m = align_vocabs(ByteTokenizer(), WordTokenizer())
    assert m is None
    assert any("DISENGAGED" in r.message for r in caplog.records)


def test_align_sized_to_model_vocabs():
    m = align_vocabs(ByteTokenizer(), ShiftedByteTokenizer())
    big = m.sized_to(CFG.vocab_size, CFG.vocab_size, target_pad=0,
                     draft_pad=0)
    assert len(big.d2t) == len(big.t2d) == CFG.vocab_size
    assert not big.identity
    # Tokenizer-range entries are preserved; the padded tail is unmapped.
    assert np.array_equal(np.asarray(big.d2t[:260]), np.asarray(m.d2t))
    assert np.all(np.asarray(big.d2t[260:]) == 0)
    assert big.scope_key() != m.scope_key()
    # Model vocab smaller than the tokenizer's tables is a hard error.
    with pytest.raises(ValueError, match="smaller than the tokenizer"):
        m.sized_to(128, CFG.vocab_size)
    # Identity survives padding only at equal vocabs (pass-through).
    ident = align_vocabs(ByteTokenizer(), ByteTokenizer())
    assert ident.sized_to(384, 384).identity
    assert np.array_equal(np.asarray(ident.sized_to(384, 384).d2t),
                          np.arange(384))


# ---------------------------------------------------------------------------
# Batcher: cross-model spec parity + accept witness
# ---------------------------------------------------------------------------


def _xmodel_map():
    m = align_vocabs(ByteTokenizer(), ShiftedByteTokenizer())
    return m.sized_to(CFG.vocab_size, CFG.vocab_size, target_pad=0,
                      draft_pad=0)


def _burst(params, draft, spec_k, draft_map=None, prompts=None):
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(**_CCFG, spec_k=spec_k),
        draft=draft,
        draft_map=draft_map,
    )
    prompts = prompts or [
        "Panel shared header, forty characters xx: alpha",
        "Panel shared header, forty characters xx: beta",
        "unrelated prompt entirely",
    ]
    try:
        return [r.text for r in _serve(b, prompts)], b.stats()
    finally:
        b.close()


def test_xmodel_spec_byte_parity_and_accept_witness(params):
    """THE PR-18 acceptance contract: greedy text byte-identical with
    cross-model speculation ON vs OFF, with at least one genuinely
    cross-model accept counted in stats, Prometheus, and the flight
    trace."""
    from llm_consensus_tpu.server.metrics import (
        SPEC_XMODEL_ACCEPTED_TOKENS,
    )
    from llm_consensus_tpu.serving import flight as _flight

    vmap = _xmodel_map()
    twin = _twin_params(params, vmap.d2t)
    want, _ = _burst(params, None, 0)
    before = SPEC_XMODEL_ACCEPTED_TOKENS.value
    got, st = _burst(params, (CFG, twin), 3, draft_map=vmap)
    assert got == want
    assert st["device_programs_spec"] >= 1
    # The twin proposes the target's chain through the remap: accepts
    # are structural, and they are cross-model accepts.
    assert st["spec_cross_model_accepted_tokens"] > 0
    assert st["spec_cross_model_accepted_tokens"] <= (
        st["spec_accepted_tokens"]
    )
    assert SPEC_XMODEL_ACCEPTED_TOKENS.value - before == (
        st["spec_cross_model_accepted_tokens"]
    )
    kinds = [e.kind for e in _flight.flight_recorder().events()]
    assert "spec_xmodel_accept" in kinds


def test_xmodel_spec_adversarial_draft_parity(params):
    """The adversarial pair (independently random draft weights) through
    the SAME non-identity map: acceptance ~0, byte parity still exact —
    alignment quality moves speed, never text."""
    vmap = _xmodel_map()
    dparams = init_params(CFG, jax.random.PRNGKey(9), dtype=jnp.float32)
    want, _ = _burst(params, None, 0)
    got, st = _burst(params, (CFG, dparams), 3, draft_map=vmap)
    assert got == want
    assert st["device_programs_spec"] >= 1


def test_xmodel_vocab_mismatch_without_map_raises(params):
    dcfg = CFG.with_(vocab_size=CFG.vocab_size + 64)
    dparams = init_params(dcfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    with pytest.raises(ValueError, match="vocab alignment map"):
        ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(**_CCFG, spec_k=3),
            draft=(dcfg, dparams),
        )


def test_xmodel_map_scopes_host_store_key(params):
    """Two batchers differing only in the vocab map must not share
    host-tier entries: the draft planes a restore installs were written
    through the map."""
    from llm_consensus_tpu.serving.offload import HostPageStore

    # Scopes are only computed for a SHARED store (a private one never
    # cross-restores by construction).
    store = HostPageStore(8 << 20)
    cconf = dict(_CCFG, host_cache_bytes=8 << 20)
    b1 = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**cconf, spec_k=3),
        draft=(CFG, params), host_store=store,
    )
    vmap = _xmodel_map()
    b2 = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**cconf, spec_k=3),
        draft=(CFG, params), draft_map=vmap, host_store=store,
    )
    try:
        assert b1._store_scope and b2._store_scope
        assert b1._store_scope != b2._store_scope
        assert vmap.scope_key() == b2._store_scope[-3:]
    finally:
        b1.close()
        b2.close()


# ---------------------------------------------------------------------------
# Probe scope (satellite: /debug/chains reports model/weights scope)
# ---------------------------------------------------------------------------


def test_prefix_probe_reports_model_scope(params):
    b = ContinuousBatcher(CFG, params, config=ContinuousConfig(**_CCFG))
    try:
        doc = b.prefix_probe([5, 6, 7])
        scope = doc["scope"]
        assert scope["model"] == CFG.name
        assert len(scope["weights"]) == 12
        # Same weights -> same scope fingerprint; different weights ->
        # different (the probe answers "WHOSE chain is resident").
        assert b.chain_scope()["weights"] == scope["weights"]
    finally:
        b.close()
    b2 = ContinuousBatcher(
        CFG,
        init_params(CFG, jax.random.PRNGKey(5), dtype=jnp.float32),
        config=ContinuousConfig(**_CCFG),
    )
    try:
        assert b2.prefix_probe([5, 6, 7])["scope"]["weights"] != (
            scope["weights"]
        )
    finally:
        b2.close()


# ---------------------------------------------------------------------------
# ReplicaSet: shared-config invariant (satellite fix)
# ---------------------------------------------------------------------------


def test_replicaset_rejects_per_replica_configs(params):
    from llm_consensus_tpu.serving.fleet import FleetConfig, ReplicaSet

    cfgs = [ContinuousConfig(**_CCFG) for _ in range(2)]
    with pytest.raises(ValueError, match="ONE shared ContinuousConfig"):
        ReplicaSet(
            CFG,
            params,
            config=cfgs,
            fleet=FleetConfig(replicas=2),
        )


# ---------------------------------------------------------------------------
# ModelSet: dispatch, engage audit, phase routing
# ---------------------------------------------------------------------------


def _mini_set(params, paired=True):
    twin = _twin_params(
        params,
        align_vocabs(ByteTokenizer(), ShiftedByteTokenizer())
        .sized_to(CFG.vocab_size, CFG.vocab_size).d2t,
    )
    specs = [
        ModelSpec(
            name="large", cfg=CFG, params=params,
            tokenizer=ByteTokenizer(),
            config=ContinuousConfig(**_CCFG, spec_k=3),
            draft_from="small" if paired else None,
        ),
        ModelSpec(
            name="small", cfg=CFG, params=twin,
            tokenizer=ShiftedByteTokenizer(),
            config=ContinuousConfig(**_CCFG),
        ),
    ]
    return ModelSet(specs, default="large")


def test_modelset_dispatch_and_routing(params):
    ms = _mini_set(params)
    be = ModelSetBackend(ms)
    try:
        eng = ms.engage_matrix()
        assert eng["large"]["cross_model_spec"] is True
        assert eng["large"]["draft_from"] == "small"
        assert eng["large"]["vocab_coverage"] > 0.99
        assert eng["small"]["cross_model_spec"] is False
        assert ms.phase_models() == {
            "propose": "small", "evaluate": "large", "refine": "large",
        }
        assert ms.admission_lanes() == ("model:large", "model:small")

        sp = SamplingParams(max_new_tokens=6, temperature=0.0)
        reqs = [
            GenerationRequest("route me", sp, model=m)
            for m in ("large", "small", None)
        ]
        outs = asyncio.run(be.generate_batch(reqs))
        assert all(o.text is not None for o in outs)
        # None routed to the default member -> identical greedy text.
        assert outs[2].text == outs[0].text
        st = ms.stats()
        assert st["per_model"]["large"]["requests"] == 2
        assert st["per_model"]["small"]["requests"] == 1
        assert st["per_model"]["large"]["tokens"] > 0

        with pytest.raises(BackendError, match="unknown model"):
            asyncio.run(
                be.generate_batch(
                    [GenerationRequest("x", sp, model="nope")]
                )
            )

        doc = be.prefix_probe(ByteTokenizer().encode("route me"))
        assert doc["scope"]["model"] == CFG.name
        assert set(doc["models"]) == {"large", "small"}
        h = be.health()
        assert h["alive"] and set(h["models"]) == {"large", "small"}
    finally:
        asyncio.run(be.close())


def test_modelset_low_coverage_pairing_warns_not_raises(params, caplog):
    """Below-threshold pairing is the DOCUMENTED disengage: a warning
    naming the member, an engine without a draft, serving continues."""
    specs = [
        ModelSpec(
            name="large", cfg=CFG, params=params,
            tokenizer=ByteTokenizer(),
            config=ContinuousConfig(**_CCFG, spec_k=3),
            draft_from="small",
        ),
        ModelSpec(
            name="small", cfg=CFG, params=params,
            tokenizer=WordTokenizer(),
            config=ContinuousConfig(**_CCFG),
        ),
    ]
    with caplog.at_level(logging.WARNING, "llm_consensus_tpu"):
        ms = ModelSet(specs, default="large")
    try:
        assert any("disengaged" in r.message for r in caplog.records)
        eng = ms.engage_matrix()
        assert eng["large"]["cross_model_spec"] is not True
        assert ms.members["large"].draft_pair is None
        assert ms.phase_models() is None
    finally:
        ms.close()


def test_bench_serve_multi_model_cpu_ab_leg():
    """The CPU-run A/B leg (acceptance): debate-shaped traffic through
    a 2-member ModelSet, identical consensus decisions spec on/off,
    cross-model accepts witnessed, tok/s gate passes, rc 0."""
    import subprocess
    import sys
    from pathlib import Path

    r = subprocess.run(
        [
            sys.executable, "bench.py", "--tiny", "--cpu",
            "--serve-multi-model", "--serve-requests", "4",
            "--serve-slots", "3", "--new-tokens", "8",
            "--prompt-len", "96", "--serve-prefill-chunk", "64",
            "--k-spec", "3", "--mm-ab-rounds", "1",
        ],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "decisions unchanged=True" in r.stdout
    assert "cross-model accepted draft tokens" in r.stdout
    assert '"unit": "tokens/sec"' in r.stdout
    assert '"status": "ok"' in r.stdout


def test_modelset_duplicate_and_unknown_member_validation(params):
    spec = ModelSpec(
        name="a", cfg=CFG, params=params,
        config=ContinuousConfig(**_CCFG),
    )
    with pytest.raises(ValueError, match="default model"):
        ModelSet([spec], default="nope")
    with pytest.raises(ValueError, match="draft_from"):
        ModelSet([
            ModelSpec(
                name="a", cfg=CFG, params=params,
                config=ContinuousConfig(**_CCFG), draft_from="ghost",
            ),
        ])
