"""Multi-host helpers (parallel/multihost.py) on the single-process
CPU mesh — the functions must degrade exactly to the single-host path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh
from llm_consensus_tpu.parallel.multihost import (
    DistributedConfig,
    host_array_to_global,
    initialize_distributed,
    local_batch_slice,
    make_multislice_mesh,
)


def test_initialize_noop_single_host(monkeypatch):
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("NUM_PROCESSES", raising=False)
    assert initialize_distributed(DistributedConfig()) is False


def test_local_batch_slice_single_process():
    per, off = local_batch_slice(32)
    assert (per, off) == (32, 0)


def test_multislice_mesh_falls_back_single_slice(cpu_devices):
    mesh = make_multislice_mesh(MeshConfig(data=4, model=2), n_slices=1)
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2


def test_multislice_mesh_rejects_ici_critical_axes():
    with pytest.raises(ValueError, match="DCN"):
        make_multislice_mesh(MeshConfig(model=8), dcn_axis="model")
    with pytest.raises(ValueError, match="DCN"):
        make_multislice_mesh(MeshConfig(expert=8), dcn_axis="expert")
    with pytest.raises(ValueError, match="not in"):
        make_multislice_mesh(MeshConfig(data=8), dcn_axis="batch")


def test_host_array_to_global_single_process(cpu_devices):
    mesh = make_mesh(MeshConfig(data=8), cpu_devices)
    x = np.arange(64, dtype=np.int32).reshape(8, 8)
    arr = host_array_to_global(x, mesh, P("data", None))
    assert isinstance(arr, jax.Array)
    np.testing.assert_array_equal(np.asarray(arr), x)
    assert arr.sharding.spec == P("data", None)


def test_dcn_axis_divisibility_check():
    with pytest.raises(ValueError, match="divisible"):
        make_multislice_mesh(MeshConfig(data=3), dcn_axis="data", n_slices=2)


def test_initialize_raises_on_explicit_config_failure(monkeypatch):
    """An explicitly configured multi-process job must NOT silently fall
    back to single-host (divergent replicas); it raises (ADVICE r1)."""
    from llm_consensus_tpu.parallel.multihost import (
        DistributedConfig,
        initialize_distributed,
    )

    from llm_consensus_tpu.parallel import compat

    monkeypatch.setattr(
        compat, "distributed_is_initialized", lambda: False
    )

    def boom(**kw):
        raise ConnectionError("coordinator unreachable")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.raises(RuntimeError, match="explicitly"):
        initialize_distributed(
            DistributedConfig(
                coordinator_address="10.0.0.1:1234",
                num_processes=2,
                process_id=0,
            )
        )
