"""Native runtime (libconsensus_rt.so): tokenizer, ring, data loader.

Skipped wholesale when the toolchain can't build the library — every
native consumer has a pure-Python fallback, which other tests cover.
"""

import threading

import numpy as np
import pytest

from llm_consensus_tpu.native import available

pytestmark = pytest.mark.skipif(
    not available(), reason="native runtime not built"
)


# ---------------------------------------------------------------------------
# Batch tokenizer parity with the Python ByteTokenizer
# ---------------------------------------------------------------------------


def test_batch_encode_matches_python_tokenizer():
    from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
    from llm_consensus_tpu.native import batch_encode

    tok = ByteTokenizer()
    texts = ["hello", "ünïcödé ☃", "", "a" * 50]
    out, lens = batch_encode(texts, max_len=32)
    for i, t in enumerate(texts):
        expected = tok.encode(t)[-32:]
        assert out[i, : lens[i]].tolist() == expected
        assert (out[i, lens[i] :] == tok.pad_id).all()


def test_batch_encode_tail_truncation_matches_python():
    from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
    from llm_consensus_tpu.native import batch_encode

    tok = ByteTokenizer()
    long = "x" * 100 + "TAIL"
    out, lens = batch_encode([long], max_len=16)
    assert out[0, : lens[0]].tolist() == tok.encode(long)[-16:]


def test_batch_decode_roundtrip_and_eos_stop():
    from llm_consensus_tpu.native import batch_decode, batch_encode

    out, lens = batch_encode(["roundtrip text", "second"], max_len=32, add_bos=False)
    texts = batch_decode(out)
    assert texts == ["roundtrip text", "second"]
    # EOS (id 2) stops the row decode.
    row = np.array([[104 + 3, 105 + 3, 2, 106 + 3]], np.int32)
    assert batch_decode(row) == ["hi"]


# ---------------------------------------------------------------------------
# Request ring
# ---------------------------------------------------------------------------


def test_ring_fifo_and_timeout():
    from llm_consensus_tpu.native import NativeRing

    r = NativeRing(2)
    assert r.push(b"a") and r.push(b"b")
    assert not r.push(b"c", timeout=0.02)  # full
    assert r.pop() == b"a"
    assert r.pop() == b"b"
    assert r.pop(timeout=0.02) is None  # empty


def test_ring_cross_thread_blocking_pop():
    from llm_consensus_tpu.native import NativeRing

    r = NativeRing(4)
    got = []

    def consumer():
        got.append(r.pop(timeout=2.0))

    t = threading.Thread(target=consumer)
    t.start()
    r.push(b"payload")
    t.join(timeout=3)
    assert got == [b"payload"]


def test_ring_close_unblocks():
    from llm_consensus_tpu.native import NativeRing

    r = NativeRing(1)
    results = []

    def consumer():
        results.append(r.pop(timeout=5.0))

    t = threading.Thread(target=consumer)
    t.start()
    r.close()
    t.join(timeout=3)
    assert results == [None]


# ---------------------------------------------------------------------------
# Data loader
# ---------------------------------------------------------------------------


def test_loader_contiguous_windows(tmp_path):
    from llm_consensus_tpu.native import NativeLoader

    shard = tmp_path / "shard.bin"
    np.arange(5000, dtype=np.int32).tofile(shard)
    ld = NativeLoader(shard, batch=4, seq=16, seed=7)
    assert ld.n_tokens == 5000
    for _ in range(5):
        b = ld.next()
        assert b.shape == (4, 16)
        assert (np.diff(b, axis=1) == 1).all()  # contiguous window
        assert (b >= 0).all() and (b < 5000).all()
    ld.close()


def test_loader_deterministic_by_seed(tmp_path):
    from llm_consensus_tpu.native import NativeLoader

    shard = tmp_path / "shard.bin"
    np.arange(2000, dtype=np.int32).tofile(shard)
    a = NativeLoader(shard, batch=2, seq=8, seed=3)
    b = NativeLoader(shard, batch=2, seq=8, seed=3)
    np.testing.assert_array_equal(a.next(), b.next())
    a.close()
    b.close()


def test_loader_missing_file(tmp_path):
    from llm_consensus_tpu.native import NativeLoader

    with pytest.raises(FileNotFoundError):
        NativeLoader(tmp_path / "nope.bin", batch=1, seq=8)
