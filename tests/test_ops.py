"""Numerics tests for core ops against naive reference implementations."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.ops.attention import causal_attention, decode_attention
from llm_consensus_tpu.ops.norms import rms_norm
from llm_consensus_tpu.ops.rope import apply_rope, rope_cos_sin
from llm_consensus_tpu.ops.activations import swiglu


def test_rms_norm_matches_numpy():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 5, 16)).astype(np.float32)
    w = rng.standard_normal(16).astype(np.float32)
    eps = 1e-5
    expected = x / np.sqrt((x**2).mean(-1, keepdims=True) + eps) * w
    got = rms_norm(jnp.asarray(x), jnp.asarray(w), eps)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=2e-4, atol=2e-5)


def test_rms_norm_preserves_dtype():
    x = jnp.ones((2, 8), jnp.bfloat16)
    w = jnp.ones((8,), jnp.bfloat16)
    assert rms_norm(x, w).dtype == jnp.bfloat16


def test_rope_preserves_norm():
    # Rotation must not change vector norms per frequency pair.
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (1, 6, 2, 8))
    positions = jnp.arange(6)[None, :]
    cos, sin = rope_cos_sin(positions, 8)
    y = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(y), axis=-1),
        rtol=1e-5,
    )


def test_rope_relative_property():
    # <rope(q,m), rope(k,n)> depends only on m-n.
    key1, key2 = jax.random.split(jax.random.PRNGKey(2))
    q = jax.random.normal(key1, (1, 1, 1, 16))
    k = jax.random.normal(key2, (1, 1, 1, 16))

    def dot_at(m, n):
        cm, sm = rope_cos_sin(jnp.array([[m]]), 16)
        cn, sn = rope_cos_sin(jnp.array([[n]]), 16)
        qm = apply_rope(q, cm, sm)
        kn = apply_rope(k, cn, sn)
        return float(jnp.sum(qm * kn))

    assert dot_at(5, 3) == pytest.approx(dot_at(12, 10), rel=1e-4)
    assert dot_at(0, 0) == pytest.approx(dot_at(7, 7), rel=1e-4)


def test_rope_position_zero_is_identity():
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 2, 8))
    cos, sin = rope_cos_sin(jnp.zeros((1, 1), jnp.int32), 8)
    np.testing.assert_allclose(
        np.asarray(apply_rope(x, cos, sin)), np.asarray(x), rtol=1e-6
    )


def _naive_attention(q, k, v):
    """Naive causal attention with explicit GQA repeat, numpy."""
    q, k, v = map(np.asarray, (q, k, v))
    b, s, h, d = q.shape
    hkv = k.shape[2]
    rep = h // hkv
    k = np.repeat(k, rep, axis=2)
    v = np.repeat(v, rep, axis=2)
    out = np.zeros_like(q)
    for bi in range(b):
        for hi in range(h):
            scores = (q[bi, :, hi] @ k[bi, :, hi].T) / np.sqrt(d)
            mask = np.tril(np.ones((s, s), bool))
            scores = np.where(mask, scores, -1e30)
            p = np.exp(scores - scores.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            out[bi, :, hi] = p @ v[bi, :, hi]
    return out


def test_causal_attention_matches_naive_gqa():
    key = jax.random.PRNGKey(4)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (2, 7, 4, 8))
    k = jax.random.normal(kk, (2, 7, 2, 8))
    v = jax.random.normal(kv, (2, 7, 2, 8))
    got = causal_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got), _naive_attention(q, k, v), rtol=2e-4, atol=2e-5
    )


def test_causality_future_keys_ignored():
    key = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 6, 2, 8))
    k = jax.random.normal(kk, (1, 6, 2, 8))
    v = jax.random.normal(kv, (1, 6, 2, 8))
    out1 = causal_attention(q, k, v)
    # Perturb the last key/value: all but the final query position unchanged.
    k2 = k.at[:, -1].add(100.0)
    v2 = v.at[:, -1].add(100.0)
    out2 = causal_attention(q, k2, v2)
    np.testing.assert_allclose(
        np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), rtol=1e-5
    )


def test_decode_attention_matches_causal_last_position():
    # Decoding the t-th token against a cache of t+1 entries must equal the
    # last row of full causal attention over t+1 tokens.
    key = jax.random.PRNGKey(6)
    kq, kk, kv = jax.random.split(key, 3)
    s, max_len = 5, 9
    q_all = jax.random.normal(kq, (1, s, 4, 8))
    k_all = jax.random.normal(kk, (1, s, 2, 8))
    v_all = jax.random.normal(kv, (1, s, 2, 8))
    full = causal_attention(q_all, k_all, v_all)

    k_cache = jnp.zeros((1, max_len, 2, 8)).at[:, :s].set(k_all)
    v_cache = jnp.zeros((1, max_len, 2, 8)).at[:, :s].set(v_all)
    got = decode_attention(
        q_all[:, s - 1 : s], k_cache, v_cache, jnp.array([s])
    )
    np.testing.assert_allclose(
        np.asarray(got[:, 0]), np.asarray(full[:, -1]), rtol=2e-4, atol=2e-5
    )


def test_decode_attention_masks_stale_slots():
    # Slots beyond valid_len must not affect the result.
    key = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 1, 2, 8))
    k_cache = jax.random.normal(kk, (1, 10, 2, 8))
    v_cache = jax.random.normal(kv, (1, 10, 2, 8))
    out1 = decode_attention(q, k_cache, v_cache, jnp.array([4]))
    k2 = k_cache.at[:, 4:].set(999.0)
    v2 = v_cache.at[:, 4:].set(-999.0)
    out2 = decode_attention(q, k2, v2, jnp.array([4]))
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=1e-5)


def test_swiglu_matches_numpy():
    rng = np.random.default_rng(8)
    x = rng.standard_normal((2, 3, 8)).astype(np.float32)
    wg = rng.standard_normal((8, 16)).astype(np.float32)
    wu = rng.standard_normal((8, 16)).astype(np.float32)
    wd = rng.standard_normal((16, 8)).astype(np.float32)

    def silu(z):
        return z / (1 + np.exp(-z))

    expected = (silu(x @ wg) * (x @ wu)) @ wd
    got = swiglu(jnp.asarray(x), jnp.asarray(wg), jnp.asarray(wu), jnp.asarray(wd))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=2e-4, atol=2e-4)
