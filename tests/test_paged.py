"""Paged KV cache + continuous batching (models/paged_cache.py,
serving/continuous.py).

Parity anchor: paged decode must produce exactly the greedy tokens of
the dense-cache generate loop — same model, same prompts.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
from llm_consensus_tpu.models.cache import KVCache
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.paged_cache import (
    NULL_PAGE,
    PagedKVCache,
    assign_pages,
    gather_seq_kv,
    release_seq,
    write_decode_kv,
    write_prefill_kv,
)
from llm_consensus_tpu.models.transformer import (
    decode_step,
    decode_step_paged,
    init_params,
    prefill,
)
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)

CFG = get_config("test-tiny")


def _params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def test_page_write_gather_roundtrip():
    cache = PagedKVCache.create(
        CFG, n_pages=8, page_size=4, max_seqs=2, pages_per_seq=3,
        dtype=jnp.float32,
    )
    cache = assign_pages(cache, jnp.int32(0), jnp.asarray([2, 5, 7]))
    L, h, d = CFG.n_layers, CFG.n_kv_heads, CFG.head_dim
    k_seq = jnp.arange(L * 8 * h * d, dtype=jnp.float32).reshape(L, 8, h, d)
    cache = write_prefill_kv(cache, jnp.int32(0), k_seq, k_seq, jnp.int32(7))
    k_g, v_g = gather_seq_kv(cache, jnp.asarray([0]))
    assert k_g.shape == (L, 1, 12, h, d)
    np.testing.assert_array_equal(np.asarray(k_g[:, 0, :8]), np.asarray(k_seq))
    # Decode write lands at position 7 = page 1 (id 5), offset 3.
    k_new = jnp.ones((L, 1, h, d), jnp.float32) * 99.0
    cache = write_decode_kv(cache, jnp.asarray([0]), k_new, k_new)
    assert int(cache.length[0]) == 8
    np.testing.assert_array_equal(
        np.asarray(cache.k[:, 5, 3]), np.asarray(k_new[:, 0])
    )
    cache = release_seq(cache, jnp.int32(0))
    assert int(cache.length[0]) == 0
    assert int(cache.page_table[0, 0]) == NULL_PAGE


def test_paged_decode_attention_kernel_matches_gather():
    """The paged Pallas kernel (scalar-prefetched page walk, online
    softmax across pages) must match the jnp gather path — ragged
    lengths, NULL pages, out-of-order tables, GQA groups included."""
    from llm_consensus_tpu.ops.attention import decode_attention
    from llm_consensus_tpu.ops.pallas.attention import paged_decode_attention

    key = jax.random.PRNGKey(0)
    b, h, hkv, d = 3, 4, 2, 128
    n_pages, pg, p_per = 10, 8, 4
    q = jax.random.normal(key, (b, h, d), jnp.float32)
    k_pool = jax.random.normal(jax.random.PRNGKey(1), (n_pages, pg, hkv, d))
    v_pool = jax.random.normal(jax.random.PRNGKey(2), (n_pages, pg, hkv, d))
    # Out-of-order page lists, unused slots on the NULL page; ragged
    # lengths incl. a page-boundary case and a minimal 1-token row.
    tables = jnp.asarray([[7, 2, 9, 0], [3, 1, 0, 0], [5, 0, 0, 0]])
    valid = jnp.asarray([19, 16, 1], jnp.int32)

    got = paged_decode_attention(
        q, k_pool, v_pool, tables, valid, interpret=True
    )
    k_seq = k_pool[tables].reshape(b, p_per * pg, hkv, d)
    v_seq = v_pool[tables].reshape(b, p_per * pg, hkv, d)
    want = decode_attention(q[:, None], k_seq, v_seq, valid)[:, 0]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_paged_decode_attention_kernel_mqa_edge():
    """MQA (one kv head, all query heads in one group) — the extreme
    GQA ratio must still match the gather path."""
    from llm_consensus_tpu.ops.attention import decode_attention
    from llm_consensus_tpu.ops.pallas.attention import paged_decode_attention

    b, h, hkv, d = 2, 4, 1, 128
    n_pages, pg, p_per = 6, 8, 3
    q = jax.random.normal(jax.random.PRNGKey(4), (b, h, d), jnp.float32)
    k_pool = jax.random.normal(jax.random.PRNGKey(5), (n_pages, pg, hkv, d))
    v_pool = jax.random.normal(jax.random.PRNGKey(6), (n_pages, pg, hkv, d))
    tables = jnp.asarray([[4, 1, 0], [2, 0, 0]])
    valid = jnp.asarray([13, 8], jnp.int32)
    got = paged_decode_attention(
        q, k_pool, v_pool, tables, valid, interpret=True
    )
    k_seq = k_pool[tables].reshape(b, p_per * pg, hkv, d)
    v_seq = v_pool[tables].reshape(b, p_per * pg, hkv, d)
    want = decode_attention(q[:, None], k_seq, v_seq, valid)[:, 0]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_decode_step_paged_kernel_matches_gather_path():
    """decode_step_paged with cfg.use_pallas routes through the paged
    kernel and must produce the same logits as the gather path."""
    from llm_consensus_tpu.models.transformer import decode_step_paged

    cache = PagedKVCache.create(
        CFG, n_pages=12, page_size=4, max_seqs=2, pages_per_seq=4
    )
    cache = assign_pages(cache, jnp.int32(0), jnp.asarray([2, 5, 7, 9]))
    cache = assign_pages(cache, jnp.int32(1), jnp.asarray([1, 3, 0, 0]))
    params = _params()
    L, hkv, d = CFG.n_layers, CFG.n_kv_heads, CFG.head_dim
    k_seq = jax.random.normal(jax.random.PRNGKey(3), (L, 8, hkv, d))
    cache = write_prefill_kv(cache, jnp.int32(0), k_seq, k_seq, jnp.int32(6))
    cache = write_prefill_kv(
        cache, jnp.int32(1), k_seq[:, :4], k_seq[:, :4], jnp.int32(3)
    )
    toks = jnp.asarray([[9], [17]], jnp.int32)
    logits_ref, _ = decode_step_paged(CFG, params, toks, cache)
    logits_krn, cache_krn = decode_step_paged(
        CFG.with_(use_pallas=True), params, toks, cache
    )
    np.testing.assert_allclose(
        np.asarray(logits_krn),
        np.asarray(logits_ref),
        rtol=2e-4,
        atol=2e-4,
    )
    assert int(cache_krn.length[0]) == 7  # write still advanced


def test_paged_decode_matches_dense():
    """Greedy decode over the paged cache == dense-cache decode_step."""
    params = _params()
    prompt = jnp.asarray(
        [[5, 6, 7, 8, 9, 10, 11, 12]], jnp.int32
    )  # [1, 8]
    steps = 6

    dense = KVCache.create(CFG, 1, 32, dtype=jnp.float32)
    logits, dense = prefill(CFG, params, prompt, jnp.asarray([8]), dense)
    dense_toks = []
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    for _ in range(steps):
        dense_toks.append(int(tok[0]))
        logits, dense = decode_step(CFG, params, tok[:, None], dense)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)

    paged = PagedKVCache.create(
        CFG, n_pages=16, page_size=4, max_seqs=2, pages_per_seq=8,
        dtype=jnp.float32,
    )
    paged = assign_pages(
        paged, jnp.int32(1), jnp.asarray([3, 9, 4, 11, 0, 0, 0, 0])
    )
    d2 = KVCache.create(CFG, 1, 8, dtype=jnp.float32)
    logits2, d2 = prefill(CFG, params, prompt, jnp.asarray([8]), d2)
    paged = write_prefill_kv(
        paged, jnp.int32(1), d2.k[:, 0], d2.v[:, 0], jnp.int32(8)
    )
    tok2 = jnp.argmax(logits2, -1).astype(jnp.int32)
    paged_toks = []
    for _ in range(steps):
        paged_toks.append(int(tok2[0]))
        full = jnp.zeros((2,), jnp.int32).at[1].set(tok2[0])
        logits2, paged = decode_step_paged(CFG, params, full[:, None], paged)
        tok2 = jnp.argmax(logits2[1:2], -1).astype(jnp.int32)

    assert paged_toks == dense_toks


@pytest.fixture
def batcher():
    b = ContinuousBatcher(
        CFG,
        _params(),
        config=ContinuousConfig(
            max_slots=4,
            page_size=16,
            n_pages=64,
            pages_per_seq=8,
            max_new_tokens=8,
            seq_buckets=(16, 32, 64),
        ),
    )
    yield b
    b.close()


def test_continuous_matches_engine_greedy(batcher):
    """Staggered continuous-batch requests == one-shot engine results."""
    prompts = ["hello world", "the quick brown fox", "abc"]
    futures = []
    for p in prompts:
        futures.append(batcher.submit(p, max_new_tokens=8))
        time.sleep(0.02)  # arrive mid-flight
    got = [f.result(timeout=120).text for f in futures]

    eng = InferenceEngine(
        CFG,
        _params(),
        engine_config=EngineConfig(
            max_new_tokens=8, seq_buckets=(16, 32, 64)
        ),
    )
    want = [
        r.text for r in eng.generate_texts(prompts, max_new_tokens=8)
    ]
    assert got == want


def test_continuous_moe_matches_engine_greedy():
    """An MoE model (Mixtral-style capacity config) serves through the
    continuous batcher and matches the engine path exactly: at serving
    shapes every program sits at/below the dense-fallback threshold
    (ModelConfig.moe_dense_decode_tokens), so both substrates trace the
    dense all-experts path consistently."""
    cfg = get_config("test-tiny-moe").with_(moe_capacity_factor=1.25)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    b = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(
            max_slots=4,
            page_size=16,
            n_pages=64,
            pages_per_seq=8,
            max_new_tokens=8,
            seq_buckets=(16, 32, 64),
        ),
    )
    try:
        prompts = ["hello world", "abc"]
        futures = [b.submit(p, max_new_tokens=8) for p in prompts]
        got = [f.result(timeout=120).text for f in futures]
    finally:
        b.close()
    eng = InferenceEngine(
        cfg,
        params,
        engine_config=EngineConfig(max_new_tokens=8, seq_buckets=(16, 32, 64)),
    )
    want = [r.text for r in eng.generate_texts(prompts, max_new_tokens=8)]
    assert got == want


def test_backend_stop_parity_local_vs_continuous(batcher):
    """Protocol matrix with stops: LocalBackend (engine path) and
    ContinuousBackend must serve IDENTICAL text for the same greedy
    requests carrying stop sequences — the seam contract the consensus
    protocol relies on when swapping substrates."""
    import asyncio

    from llm_consensus_tpu.backends.base import (
        GenerationRequest,
        SamplingParams,
    )
    from llm_consensus_tpu.backends.local import LocalBackend
    from llm_consensus_tpu.serving.continuous import ContinuousBackend

    eng = InferenceEngine(
        CFG,
        _params(),
        engine_config=EngineConfig(max_new_tokens=8, seq_buckets=(16, 32, 64)),
    )
    # Carve stops out of real greedy output so they actually trigger;
    # keep only prompts whose output is long enough to carve from.
    probe_prompts = ["hello world", "abc", "the quick", "zed"]
    base = [
        r.text
        for r in eng.generate_texts(probe_prompts, max_new_tokens=8)
    ]
    usable = [(p, t) for p, t in zip(probe_prompts, base) if len(t) >= 4]
    if not usable:
        pytest.skip("all outputs too short to carve stops from")
    reqs = [
        GenerationRequest(
            prompt=p,
            params=SamplingParams(max_new_tokens=8, stop=(t[2:4],)),
        )
        for p, t in usable
    ]
    local = asyncio.run(LocalBackend(eng).generate_batch(reqs))
    cont = asyncio.run(ContinuousBackend(batcher).generate_batch(reqs))
    assert [r.text for r in local] == [r.text for r in cont]
    assert all(s not in r.text for r, q in zip(local, reqs) for s in q.params.stop)


def test_continuous_pool_exhaustion_recovers():
    """More requests than pool pages: later ones wait, all complete."""
    b = ContinuousBatcher(
        CFG,
        _params(),
        config=ContinuousConfig(
            max_slots=2,
            page_size=16,
            n_pages=5,  # 4 usable pages; each request needs 2
            pages_per_seq=4,
            max_new_tokens=4,
            seq_buckets=(16,),
        ),
    )
    try:
        futures = [b.submit(f"q{i}", max_new_tokens=4) for i in range(5)]
        outs = [f.result(timeout=120) for f in futures]
        assert len(outs) == 5
        assert all(isinstance(o.text, str) and o.num_tokens >= 1 for o in outs)
    finally:
        b.close()


def test_batcher_stats(batcher):
    before = batcher.stats()
    assert before["completed_requests"] == 0
    assert before["total_pages"] == 63
    batcher.submit("count me", max_new_tokens=4).result(timeout=120)
    after = batcher.stats()
    assert after["completed_requests"] == 1
    assert after["generated_tokens"] >= 1
    assert after["free_pages"] == before["free_pages"]  # pages returned
    assert after["active_slots"] == 0


def test_seed_reproducible_across_batch_states(batcher):
    """Same (prompt, seed, temperature) gives the same text whether it
    runs alone or alongside other requests."""
    alone = batcher.submit("xyz", temperature=1.0, seed=7).result(timeout=120)
    futs = [
        batcher.submit(p, temperature=1.0, seed=7 + i)
        for i, p in enumerate(["aaa", "xyz", "bbb"], start=0)
    ]
    # The "xyz" row used seed 8 here; resubmit with seed 7 amid traffic.
    crowd = batcher.submit("xyz", temperature=1.0, seed=7)
    [f.result(timeout=120) for f in futs]
    assert crowd.result(timeout=120) == alone
    # Different seeds should (overwhelmingly) differ on a tiny random
    # model with temperature 1.
    other = batcher.submit("xyz", temperature=1.0, seed=8).result(timeout=120)
    assert other != alone


def test_impossible_pool_request_fails_fast():
    """pages_per_seq would allow it but the pool can never satisfy it."""
    b = ContinuousBatcher(
        CFG,
        _params(),
        config=ContinuousConfig(
            max_slots=2,
            page_size=16,
            n_pages=4,  # 3 usable
            pages_per_seq=8,
            max_new_tokens=64,
            seq_buckets=(16,),
        ),
    )
    try:
        with pytest.raises(ValueError, match="pool"):
            b.submit("hi", max_new_tokens=64).result(timeout=60)
    finally:
        b.close()


def test_zero_max_new_tokens_rejected(batcher):
    with pytest.raises(ValueError, match="max_new_tokens"):
        batcher.submit("hi", max_new_tokens=0)


def test_oversized_request_rejected():
    b = ContinuousBatcher(
        CFG,
        _params(),
        config=ContinuousConfig(
            max_slots=2,
            page_size=16,
            n_pages=32,
            pages_per_seq=2,  # max 32 tokens total
            max_new_tokens=64,
            seq_buckets=(16,),
        ),
    )
    try:
        with pytest.raises(ValueError, match="pages"):
            b.submit("hi", max_new_tokens=64).result(timeout=60)
    finally:
        b.close()


# ---------------------------------------------------------------------------
# ContinuousBackend: the consensus protocol over token-level batching
# (VERDICT r2 #8 — serving exposed through the Backend seam)
# ---------------------------------------------------------------------------


def test_continuous_backend_generate_batch(batcher):
    """generate_batch over the batcher returns per-request results."""
    import asyncio

    from llm_consensus_tpu.backends.base import (
        Backend,
        GenerationRequest,
        SamplingParams,
    )
    from llm_consensus_tpu.serving.continuous import ContinuousBackend

    backend = ContinuousBackend(batcher)
    assert isinstance(backend, Backend)
    reqs = [
        GenerationRequest(
            prompt=p, params=SamplingParams(max_new_tokens=6)
        )
        for p in ["one", "two", "three"]
    ]
    results = asyncio.run(backend.generate_batch(reqs))
    assert len(results) == 3
    assert all(r.num_tokens >= 1 for r in results)


def test_continuous_backend_per_request_sampling_passthrough(batcher):
    """Per-request top_k/top_p ride as decode-step data now — a request
    with its own sampler settings must serve (no recompile-guard
    rejection), and top_k=1 must reduce to the greedy result."""
    import asyncio

    from llm_consensus_tpu.backends.base import (
        GenerationRequest,
        SamplingParams,
    )
    from llm_consensus_tpu.serving.continuous import ContinuousBackend

    backend = ContinuousBackend(batcher)
    greedy, k1 = asyncio.run(
        backend.generate_batch(
            [
                GenerationRequest(
                    prompt="same prompt",
                    params=SamplingParams(max_new_tokens=6),
                ),
                GenerationRequest(
                    prompt="same prompt",
                    params=SamplingParams(
                        max_new_tokens=6, temperature=0.9, top_k=1, seed=5
                    ),
                ),
            ]
        )
    )
    # top_k=1 sampling == greedy, regardless of temperature/seed.
    assert k1.text == greedy.text


def test_continuous_batcher_stop_sequences(batcher):
    """The engine stop contract on the continuous batcher: text trims at
    the earliest stop, and the row retires as soon as the stop appears
    (multi-token stops end decoding immediately — every token is
    host-checked)."""
    full = batcher.submit("tell me a fact", max_new_tokens=8).result(60)
    if len(full.text) < 4:
        pytest.skip("output too short to carve a stop from")
    stop = full.text[2:4]  # a MULTI-char stop that lands mid-output
    r = batcher.submit(
        "tell me a fact", max_new_tokens=8, stop=[stop]
    ).result(60)
    assert r.text == full.text[: full.text.find(stop)]
    assert stop not in r.text
    assert r.num_tokens <= full.num_tokens  # retired early, not trimmed late


def test_coordinator_protocol_over_continuous_backend(batcher):
    """The full consensus protocol rides token-level batching: panel
    fan-outs arrive as generate_batch lists and interleave at decode-step
    granularity. A random-weight tiny model never produces parseable
    verdicts, so every round dissents and the round cap terminates —
    exercising propose -> evaluate -> refine end to end."""
    import asyncio

    from llm_consensus_tpu.backends.base import SamplingParams
    from llm_consensus_tpu.consensus.coordinator import (
        Coordinator,
        CoordinatorConfig,
    )
    from llm_consensus_tpu.consensus.personas import default_panel
    from llm_consensus_tpu.serving.continuous import ContinuousBackend

    backend = ContinuousBackend(batcher)
    coord = Coordinator(
        panel=default_panel(),
        backend=backend,
        config=CoordinatorConfig(
            max_rounds=2,
            seed=0,
            sampling=SamplingParams(max_new_tokens=4),
        ),
    )
    res = asyncio.run(coord.run("What is 2+2?"))
    assert res.answer  # some text was produced
    assert res.rounds <= 2
    assert res.endorsed is False  # garbage verdicts parse as dissent


def test_overlong_prompt_rejected_when_truncation_disabled():
    """truncate_prompts=False surfaces over-long prompts instead of
    silently dropping their head (ADVICE r1)."""
    b = ContinuousBatcher(
        CFG,
        _params(),
        config=ContinuousConfig(
            max_slots=2,
            page_size=16,
            n_pages=32,
            pages_per_seq=8,
            max_new_tokens=4,
            seq_buckets=(16,),
            truncate_prompts=False,
        ),
    )
    try:
        with pytest.raises(ValueError, match="bucket"):
            b.submit("x" * 100)  # ~100 byte tokens > 16-token bucket
    finally:
        b.close()


def test_paged_decode_attention_kernel_sliding_window():
    """window > 0 (Mistral): only the last `window` slots attend — the
    kernel must match the gather path's windowed mask, including a
    window that starts mid-page and a row shorter than the window."""
    from llm_consensus_tpu.ops.attention import decode_attention
    from llm_consensus_tpu.ops.pallas.attention import paged_decode_attention

    b, h, hkv, d = 2, 4, 2, 128
    n_pages, pg, p_per = 8, 8, 4
    q = jax.random.normal(jax.random.PRNGKey(7), (b, h, d), jnp.float32)
    k_pool = jax.random.normal(jax.random.PRNGKey(8), (n_pages, pg, hkv, d))
    v_pool = jax.random.normal(jax.random.PRNGKey(9), (n_pages, pg, hkv, d))
    tables = jnp.asarray([[3, 6, 1, 2], [5, 4, 0, 0]])
    valid = jnp.asarray([27, 6], jnp.int32)  # window mid-page / short row
    for window in (10, 4):
        got = paged_decode_attention(
            q, k_pool, v_pool, tables, valid, window=window, interpret=True
        )
        k_seq = k_pool[tables].reshape(b, p_per * pg, hkv, d)
        v_seq = v_pool[tables].reshape(b, p_per * pg, hkv, d)
        want = decode_attention(
            q[:, None], k_seq, v_seq, valid, window=window
        )[:, 0]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
            err_msg=f"window={window}",
        )


def test_decode_step_paged_kernel_sliding_window_config():
    """A sliding-window config (Mistral-style) routed through the paged
    kernel must match the gather path's logits."""
    from llm_consensus_tpu.models.transformer import decode_step_paged

    wcfg = CFG.with_(sliding_window=6)
    cache = PagedKVCache.create(
        wcfg, n_pages=10, page_size=4, max_seqs=2, pages_per_seq=4
    )
    cache = assign_pages(cache, jnp.int32(0), jnp.asarray([2, 5, 7, 9]))
    cache = assign_pages(cache, jnp.int32(1), jnp.asarray([1, 3, 0, 0]))
    params = _params()
    L, hkv, d = wcfg.n_layers, wcfg.n_kv_heads, wcfg.head_dim
    k_seq = jax.random.normal(jax.random.PRNGKey(10), (L, 8, hkv, d))
    cache = write_prefill_kv(cache, jnp.int32(0), k_seq, k_seq, jnp.int32(8))
    cache = write_prefill_kv(
        cache, jnp.int32(1), k_seq[:, :4], k_seq[:, :4], jnp.int32(3)
    )
    toks = jnp.asarray([[11], [23]], jnp.int32)
    logits_ref, _ = decode_step_paged(wcfg, params, toks, cache)
    logits_krn, _ = decode_step_paged(
        wcfg.with_(use_pallas=True), params, toks, cache
    )
    np.testing.assert_allclose(
        np.asarray(logits_krn), np.asarray(logits_ref),
        rtol=2e-4, atol=2e-4,
    )


def test_hit_stop_confirms_window_hit_against_full_text():
    """r4 advisor: a merge-based tokenizer can decode a TAIL WINDOW
    differently from the full text at the window head, so a window-only
    stop check can false-positive and retire the row early — output
    silently truncated while the final earliest_stop_cut finds no stop.
    _hit_stop must confirm candidate hits against the full decode."""
    from types import SimpleNamespace

    from llm_consensus_tpu.serving.continuous import ContinuousBatcher
    from llm_consensus_tpu.utils.stops import VisibleIdFilter

    class MergeTok:
        """Context-sensitive decode: id 2 alone is "b", but after id 1
        the pair [1, 2] merges to "aX" (no "b" anywhere)."""

        eos_id = 99

        def decode(self, ids):
            out = []
            prev = None
            for t in ids:
                if prev == 1 and t == 2:
                    out[-1] = "aX"
                else:
                    out.append({1: "a", 2: "b"}.get(t, "?"))
                prev = t
            return "".join(out)

    tok = MergeTok()
    host = SimpleNamespace(
        tokenizer=tok,
        _vis_filter=VisibleIdFilter(tok, skip_ids=(tok.eos_id,)),
    )
    host._decoded_text = lambda s: ContinuousBatcher._decoded_text(host, s)
    slot = SimpleNamespace(
        generated=[1, 2],
        request=SimpleNamespace(stop=("b",), stop_window=1),
    )
    # Window [2] decodes "b" (candidate hit); full text "aX" has no
    # stop -> must NOT retire.
    assert not ContinuousBatcher._hit_stop(host, slot)
    # A genuine stop (newest token decodes "b" in the full text too)
    # still hits.
    slot2 = SimpleNamespace(
        generated=[1, 2, 2],
        request=SimpleNamespace(stop=("b",), stop_window=1),
    )
    assert ContinuousBatcher._hit_stop(host, slot2)


def test_continuous_batcher_on_mesh_matches_single_device():
    """Mesh batcher (slots + page pool over `data`, kv heads over
    `model`, slot-affinity page allocation) serves byte-identical text
    to the single-device batcher for the same greedy burst (round-4
    verdict item 4)."""
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    params = _params()
    ccfg = ContinuousConfig(
        max_slots=4,
        page_size=16,
        n_pages=64,
        pages_per_seq=8,
        max_new_tokens=8,
        seq_buckets=(16, 32, 64),
    )
    prompts = ["hello world", "the quick brown fox", "abc", "mesh", "zed"]

    plain = ContinuousBatcher(CFG, params, config=ccfg)
    try:
        want = [
            f.result(timeout=120).text
            for f in [plain.submit(p) for p in prompts]
        ]
    finally:
        plain.close()

    mesh = make_mesh(MeshConfig(data=4, model=2))
    sharded = ContinuousBatcher(CFG, params, config=ccfg, mesh=mesh)
    try:
        got = [
            f.result(timeout=120).text
            for f in [sharded.submit(p) for p in prompts]
        ]
        stats = sharded.stats()
    finally:
        sharded.close()
    assert got == want
    assert stats["completed_requests"] == len(prompts)
    assert stats["free_pages"] == 63  # all pages returned (page 0 reserved)


def test_mesh_batcher_rejects_indivisible_shapes():
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=4, model=2))
    with pytest.raises(ValueError, match="multiples of the mesh"):
        ContinuousBatcher(
            CFG,
            _params(),
            config=ContinuousConfig(
                max_slots=3, page_size=16, n_pages=64, pages_per_seq=8
            ),
            mesh=mesh,
        )


def test_continuous_chunk_size_invariance():
    """steps_per_sync AND pipeline_depth are pure throughput knobs:
    chunk 1/4 x depth 1/2 all serve identical text for the same greedy
    AND sampled requests (the per-token PRNG stream is (seed, index),
    independent of how many steps ride one program or how many
    programs ride in flight)."""
    params = _params()

    def run(chunk, depth):
        b = ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(
                max_slots=4,
                page_size=16,
                n_pages=64,
                pages_per_seq=8,
                max_new_tokens=8,
                seq_buckets=(16, 32, 64),
                steps_per_sync=chunk,
                pipeline_depth=depth,
            ),
        )
        try:
            futs = [
                b.submit("hello world"),
                b.submit("the quick", temperature=0.9, seed=7),
                b.submit("abc", temperature=1.3, seed=11),
            ]
            return [f.result(timeout=120).text for f in futs]
        finally:
            b.close()

    want = run(1, 1)
    assert run(4, 1) == want
    assert run(1, 2) == want
    assert run(4, 2) == want
