"""Refcounted copy-on-write page tables + chunked prefill (PR 2).

Covers the host-side allocator (PagePool), the prefix radix tree
(PrefixRegistry), the copy_page device op, and the ContinuousBatcher's
shared-prefix admission path: share/CoW/release lifecycle, boundary-page
copy, pool exhaustion under sharing, and decode-output parity against
the legacy blocking dense-prefill path (the acceptance criterion: page
sharing + chunked prefill must be output-identical to per-request dense
prefill, CPU, seeded).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.paged_cache import (
    NULL_PAGE,
    PagedKVCache,
    PagePool,
    PrefixRegistry,
    copy_page,
)
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)

CFG = get_config("test-tiny")


def _params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


# ---------------------------------------------------------------------------
# PagePool: refcount lifecycle
# ---------------------------------------------------------------------------


def test_page_pool_alloc_share_release_lifecycle():
    pool = PagePool(range(1, 6))  # 5 pages
    assert pool.available == 5
    a, b = pool.alloc(2)
    assert pool.available == 3
    assert pool.refcount(a) == 1
    pool.share(a)  # second holder
    assert pool.refcount(a) == 2
    pool.release(a)  # first holder gone: page stays allocated
    assert pool.refcount(a) == 1
    assert pool.available == 3
    pool.release(a)  # last holder: back on the free list
    assert pool.refcount(a) == 0
    assert pool.available == 4
    pool.release(b)
    assert pool.available == 5


def test_page_pool_guards():
    pool = PagePool(range(1, 4))
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.alloc(4)
    with pytest.raises(ValueError, match="not allocated"):
        pool.release(1)
    with pytest.raises(ValueError, match="not allocated"):
        pool.share(2)
    # A failed alloc must not have leaked pages.
    assert pool.available == 3


# ---------------------------------------------------------------------------
# PrefixRegistry: radix match / boundary / eviction
# ---------------------------------------------------------------------------


def _registry(pg=4, n=32):
    pool = PagePool(range(1, n))
    return pool, PrefixRegistry(pool, pg)


def test_registry_match_shares_full_pages_and_refcounts():
    pool, reg = _registry()
    ids = list(range(100, 112))  # 3 full pages of 4
    pages = pool.alloc(3)
    created = reg.register(ids, pages)
    assert [end for _, end in created] == [4, 8, 12]
    for node, _ in created:
        reg.mark_ready(node)
    # Registry holds one ref on top of the owner's.
    assert all(pool.refcount(p) == 2 for p in pages)

    # Same 8-token prefix, different tail: the two full pages map.
    other = ids[:8] + [7, 7, 7, 7]
    m = reg.match(other)
    assert m.pages == pages[:2]
    assert m.shared_tokens == 8
    assert all(pool.refcount(p) == 3 for p in pages[:2])
    # Divergent tail: page 3 offered for boundary copy (common run 0 of
    # page tokens [108..111] vs [7,7,7,7] -> no boundary).
    assert m.boundary_page is None

    # The full prompt itself caps at len-1: the last page must NOT be
    # fully shared (>= 1 token left to prefill for first-token logits).
    m2 = reg.match(ids)
    assert m2.shared_tokens == 8
    assert m2.boundary_page == pages[2]
    assert m2.boundary_common == 3  # min(4, len(rem) - 1)


def test_registry_boundary_respects_min_and_readiness():
    pool, reg = _registry()
    ids = list(range(50, 58))  # 2 full pages
    pages = pool.alloc(2)
    created = reg.register(ids, pages)
    # Content pending: no boundary candidate until mark_ready.
    probe = ids[:4] + [ids[4], 9, 9, 9]
    m = reg.match(probe)
    assert m.pages == pages[:1] and m.boundary_page is None
    for node, _ in created:
        reg.mark_ready(node)
    m2 = reg.match(probe)
    assert m2.boundary_page == pages[1]
    assert m2.boundary_common == 1
    # min_boundary prunes trivial overlaps (the every-prompt-shares-BOS
    # case).
    m3 = reg.match(probe, min_boundary=2)
    assert m3.boundary_page is None
    for m_ in (m, m2, m3):
        for p in m_.pages:
            pool.release(p)


def test_registry_evicts_lru_leaves_only_when_unreferenced():
    pool, reg = _registry(pg=4, n=8)  # 7 pages
    ids_a = list(range(10, 18))
    pages_a = pool.alloc(2)
    for node, _ in reg.register(ids_a, pages_a):
        reg.mark_ready(node)
    # Owner releases: pages now registry-only (reclaimable).
    for p in pages_a:
        pool.release(p)
    assert reg.reclaimable_pages() == 2
    assert pool.available == 5
    # Eviction frees leaves first; the chain root survives until its
    # child goes.
    assert reg.evict(1) == 1
    assert pool.available == 6
    assert len(reg) == 1
    assert reg.evict(5) == 1  # only one page left to free
    assert pool.available == 7
    assert len(reg) == 0


# ---------------------------------------------------------------------------
# copy_page device op
# ---------------------------------------------------------------------------


def test_copy_page_copies_one_page_all_layers():
    cache = PagedKVCache.create(CFG, n_pages=4, page_size=8, max_seqs=2,
                                pages_per_seq=2)
    k = jnp.arange(np.prod(cache.k.shape), dtype=jnp.float32).reshape(
        cache.k.shape
    ).astype(cache.k.dtype)
    cache = PagedKVCache(k=k, v=k + 1, page_table=cache.page_table,
                         length=cache.length)
    out = copy_page(cache, jnp.int32(1), jnp.int32(3))
    np.testing.assert_array_equal(np.asarray(out.k[:, 3]),
                                  np.asarray(cache.k[:, 1]))
    np.testing.assert_array_equal(np.asarray(out.v[:, 3]),
                                  np.asarray(cache.v[:, 1]))
    # Other pages untouched.
    np.testing.assert_array_equal(np.asarray(out.k[:, 2]),
                                  np.asarray(cache.k[:, 2]))


# ---------------------------------------------------------------------------
# ContinuousBatcher: shared-prefix admission
# ---------------------------------------------------------------------------

_HEADER = "Panel shared header for every persona, forty ch: "  # 49 chars
_CCFG = dict(
    max_slots=4,
    page_size=16,
    n_pages=64,
    pages_per_seq=8,
    max_new_tokens=8,
    seq_buckets=(16, 32, 64),
)


def _serve(batcher, prompts, **kw):
    futs = [batcher.submit(p, **kw) for p in prompts]
    return [f.result(timeout=120) for f in futs]


def test_shared_prefix_parity_and_single_prefill():
    """The acceptance criterion: N same-prefix requests served with page
    sharing + chunked prefill produce IDENTICAL text to the legacy
    blocking dense per-request prefill path, and the shared prefix's
    full pages prefill once — every later admission maps them
    (prefix_pages_shared counts 2 pages x (N-1) admissions)."""
    params = _params()
    prompts = [_HEADER + f"Q{i}: what is {i}+{i}?" for i in range(6)]

    legacy = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**_CCFG, prefill_chunk=0, share_prefix=False),
    )
    try:
        want = [r.text for r in _serve(legacy, prompts)]
    finally:
        legacy.close()

    shared = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**_CCFG, prefill_chunk=16, share_prefix=True),
    )
    try:
        got = [r.text for r in _serve(shared, prompts)]
        stats = shared.stats()
    finally:
        shared.close()

    assert got == want
    # Header = 50 ids (BOS + 49 bytes) -> 3 full pages of 16 are common
    # to every prompt; the first admission prefills them, the other 5
    # map them (the <= 1-full-prefill acceptance assertion: any second
    # prefill of the prefix would show up as missing shares here).
    assert stats["prefix_pages_shared"] == 3 * (len(prompts) - 1)
    assert stats["prefix_hits"] == len(prompts) - 1
    assert stats["prefill_chunks"] > 0
    # All pages come home: shared pages' refcounts drained to the
    # registry's own hold (still cached => reclaimable => free).
    assert stats["free_pages"] == stats["total_pages"]
    assert stats["cached_pages"] > 0


def test_boundary_page_copy_on_write():
    """A prefix ending mid-page rides copy_page: the donor's boundary
    page is COPIED into the successor's private page (never shared),
    output stays parity-exact, and decode writes never touch the
    donor's pages."""
    params = _params()
    # Common run = BOS + 40 bytes = 41 ids: 2 full pages (32) + a
    # 9-token boundary run into page 3 (>= min_boundary pg//4 = 4).
    common = "Forty common characters of shared text."  # 40 chars
    prompts = [common + " tail one", common + " tail two"]

    legacy = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**_CCFG, prefill_chunk=0, share_prefix=False),
    )
    try:
        want = [r.text for r in _serve(legacy, prompts)]
    finally:
        legacy.close()

    shared = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**_CCFG, prefill_chunk=16, share_prefix=True),
    )
    try:
        # Serialize so the donor's prefill completes (boundary copies
        # require READY content; a concurrent burst falls back to
        # recompute for the boundary while still sharing full pages).
        got = [_serve(shared, [p])[0].text for p in prompts]
        stats = shared.stats()
    finally:
        shared.close()

    assert got == want
    assert stats["prefix_pages_shared"] == 2  # full pages mapped once
    assert stats["prefix_pages_copied"] == 1  # the boundary page
    assert stats["free_pages"] == stats["total_pages"]


def test_pool_exhaustion_under_sharing_recovers():
    """More same-prefix requests than the pool can hold unshared: the
    shared pages + registry eviction keep admissions flowing and every
    request completes (the no-deadlock property under sharing)."""
    params = _params()
    b = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(
            max_slots=2,
            page_size=16,
            n_pages=11,  # 10 usable; unshared need is 5 pages each
            pages_per_seq=8,
            max_new_tokens=4,
            seq_buckets=(16, 32, 64),
            prefill_chunk=16,
            share_prefix=True,
        ),
    )
    try:
        prompts = [_HEADER + f"q{i}" for i in range(6)]
        outs = _serve(b, prompts, max_new_tokens=4)
        stats = b.stats()
    finally:
        b.close()
    assert len(outs) == 6
    assert all(isinstance(o.text, str) and o.num_tokens >= 1 for o in outs)
    assert stats["prefix_pages_shared"] > 0
    assert stats["free_pages"] == stats["total_pages"]


def test_prefill_stall_histogram_populated():
    """Chunked prefill records one bounded stall observation per chunk
    into gateway_prefill_stall_seconds (the decode-not-blocked
    acceptance signal: stalls exist, and there are as many as chunks —
    never one whole-prompt blocking stall per admission)."""
    from llm_consensus_tpu.server.metrics import PREFILL_STALL_SECONDS

    params = _params()
    before = PREFILL_STALL_SECONDS.count
    b = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**_CCFG, prefill_chunk=16, share_prefix=True),
    )
    try:
        _serve(b, [_HEADER + "stall probe"])
        stats = b.stats()
    finally:
        b.close()
    assert PREFILL_STALL_SECONDS.count - before == stats["prefill_chunks"]
    assert stats["prefill_chunks"] >= 2  # 60-ids prompt, 16-token chunks


def test_gateway_exports_prefix_metrics_over_continuous_backend():
    """End-to-end wiring: gateway -> ContinuousBackend -> batcher, with
    the shared-prefix counters landing in GET /metrics (the process
    registry the gateway scrapes by default)."""
    from llm_consensus_tpu.server.gateway import (
        Gateway,
        GatewayConfig,
        GatewayThread,
    )
    from llm_consensus_tpu.server.client import GatewayClient
    from llm_consensus_tpu.server.metrics import PREFIX_PAGES_SHARED
    from llm_consensus_tpu.serving.continuous import ContinuousBackend

    params = _params()
    batcher = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**_CCFG, prefill_chunk=16, share_prefix=True),
    )
    gw = Gateway(
        ContinuousBackend(batcher), config=GatewayConfig(port=0)
    )
    handle = GatewayThread(gw).start()
    client = GatewayClient("127.0.0.1", handle.port)
    before = PREFIX_PAGES_SHARED.value
    try:
        for i in range(2):
            out = client.generate(
                _HEADER + f"gateway q{i}", max_new_tokens=4
            )
            assert isinstance(out["text"], str)
        text = client.metrics()
    finally:
        handle.drain()
        batcher.close()
    assert "gateway_prefix_pages_shared" in text
    assert "gateway_prefill_stall_seconds_bucket" in text
    assert PREFIX_PAGES_SHARED.value - before == 3  # 3 header pages mapped


def test_shared_prefix_concurrent_burst_matches_sequential():
    """The panel shape: all N submitted at once. Later admissions map
    pages the FIRST request is still prefilling (registration happens
    at admission; readiness gates the reads) — outputs must equal the
    one-at-a-time run."""
    params = _params()
    prompts = [_HEADER + f"persona {i} answers" for i in range(5)]
    cfgkw = dict(**_CCFG, prefill_chunk=16, share_prefix=True)

    solo = ContinuousBatcher(CFG, params, config=ContinuousConfig(**cfgkw))
    try:
        want = [_serve(solo, [p])[0].text for p in prompts]
    finally:
        solo.close()

    burst = ContinuousBatcher(CFG, params, config=ContinuousConfig(**cfgkw))
    try:
        got = [r.text for r in _serve(burst, prompts)]
        stats = burst.stats()
    finally:
        burst.close()
    assert got == want
    assert stats["prefix_pages_shared"] == 3 * (len(prompts) - 1)
