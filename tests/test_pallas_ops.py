"""Pallas kernels vs jnp reference ops (interpret mode on CPU).

The same kernels compile via Mosaic on real TPUs; these tests pin the
numerics against the reference implementations in
:mod:`llm_consensus_tpu.ops`.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.ops.attention import causal_attention, decode_attention
from llm_consensus_tpu.ops.norms import rms_norm
from llm_consensus_tpu.ops.pallas import (
    flash_causal_attention,
    flash_decode_attention,
    fused_rms_norm,
)


def _qkv(b=2, s=64, h=4, hkv=2, d=16, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), dtype)
    k = jax.random.normal(ks[1], (b, s, hkv, d), dtype)
    v = jax.random.normal(ks[2], (b, s, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("blk_q", [16, 32, 64])
def test_flash_causal_matches_reference(blk_q):
    q, k, v = _qkv()
    ref = causal_attention(q, k, v)
    got = flash_causal_attention(q, k, v, blk_q=blk_q, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_flash_causal_mha_no_gqa():
    q, k, v = _qkv(h=4, hkv=4)
    ref = causal_attention(q, k, v)
    got = flash_causal_attention(q, k, v, blk_q=32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_flash_causal_rejects_ragged_block():
    q, k, v = _qkv(s=48)
    with pytest.raises(ValueError):
        flash_causal_attention(q, k, v, blk_q=32, interpret=True)


def test_flash_decode_matches_reference():
    b, h, hkv, d, max_len = 3, 4, 2, 16, 32
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, 1, h, d), jnp.float32)
    k_cache = jax.random.normal(ks[1], (b, max_len, hkv, d), jnp.float32)
    v_cache = jax.random.normal(ks[2], (b, max_len, hkv, d), jnp.float32)
    valid = jnp.array([1, 17, 32], jnp.int32)  # ragged fills incl. edges

    ref = decode_attention(q, k_cache, v_cache, valid)
    got = flash_decode_attention(q, k_cache, v_cache, valid, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_fused_rms_norm_matches_reference():
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 33, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(3), (64,)) * 0.1 + 1.0
    ref = rms_norm(x, w, 1e-5)
    got = fused_rms_norm(x, w, 1e-5, blk=16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_pallas_model_matches_jnp_model_end_to_end():
    """Greedy generate with use_pallas=True must equal the jnp-op model."""
    from llm_consensus_tpu.engine.generate import generate
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = jnp.array([[5, 9, 13, 17, 21, 2, 7, 3]], jnp.int32)
    lengths = jnp.array([8], jnp.int32)
    kw = dict(max_new_tokens=4, eos_id=-1)

    ref = generate(
        cfg, params, prompt, lengths, jax.random.PRNGKey(0), jnp.zeros(1), **kw
    )
    got = generate(
        cfg.with_(use_pallas=True),
        params,
        prompt,
        lengths,
        jax.random.PRNGKey(0),
        jnp.zeros(1),
        **kw,
    )
    assert got.tokens.tolist() == ref.tokens.tolist()


def test_fused_rms_norm_bf16_output_dtype():
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 64)).astype(jnp.bfloat16)
    w = jnp.ones((64,), jnp.bfloat16)
    got = fused_rms_norm(x, w, interpret=True)
    assert got.dtype == jnp.bfloat16
