"""Mesh / sharding / ring-attention tests on an 8-device CPU mesh.

SURVEY.md §4: multi-device behavior must be testable without TPUs via
``xla_force_host_platform_device_count`` (set in conftest.py). The same
sharded programs run unchanged on a real slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import forward, init_params
from llm_consensus_tpu.ops.attention import causal_attention
from llm_consensus_tpu.parallel.mesh import MeshConfig, best_mesh_for, make_mesh
from llm_consensus_tpu.parallel.partitioning import (
    batch_pspec,
    param_pspecs,
    shard_params,
)
from llm_consensus_tpu.parallel.ring import ring_attention_sharded

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 simulated devices"
)


# ---------------------------------------------------------------------------
# Mesh
# ---------------------------------------------------------------------------


def test_make_mesh_default_all_data():
    mesh = make_mesh()
    assert mesh.shape["data"] == len(jax.devices())
    assert mesh.shape["model"] == 1


def test_make_mesh_shapes_and_validation():
    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2))
    assert mesh.shape == {
        "data": 2,
        "pipe": 1,
        "model": 2,
        "expert": 1,
        "seq": 2,
    }
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(data=3))


def test_best_mesh_for():
    cfg = best_mesh_for(8, want_model=2, want_seq=2)
    assert (cfg.data, cfg.model, cfg.seq) == (2, 2, 2)
    with pytest.raises(ValueError):
        best_mesh_for(8, want_model=3)


# ---------------------------------------------------------------------------
# Param sharding rules
# ---------------------------------------------------------------------------


def test_param_pspecs_cover_dense_and_moe():
    for preset in ("test-tiny", "test-tiny-moe"):
        cfg = get_config(preset)
        params = init_params(cfg, jax.random.PRNGKey(0))
        specs = param_pspecs(params)
        flat_p = jax.tree_util.tree_leaves_with_path(params)
        flat_s = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        assert len(flat_p) == len(flat_s)
        for (path, leaf), spec in zip(flat_p, flat_s):
            assert leaf.ndim == len(spec), f"{path}: {leaf.shape} vs {spec}"


def test_shard_params_places_on_mesh_and_forward_matches():
    """TP-sharded forward must equal the single-device result."""
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jnp.arange(12, dtype=jnp.int32).reshape(2, 6) % cfg.vocab_size
    expected = forward(cfg, params, tokens)

    mesh = make_mesh(MeshConfig(data=2, model=2, expert=2))
    sharded = shard_params(params, mesh)
    wq = sharded["blocks"]["wq"]
    assert wq.sharding.spec == P(None, None, "model")

    tok_sharding = NamedSharding(mesh, batch_pspec())
    tokens_sharded = jax.device_put(tokens, tok_sharding)
    got = jax.jit(lambda p, t: forward(cfg, p, t))(sharded, tokens_sharded)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-4
    )


def test_moe_params_shard_over_expert_axis():
    cfg = get_config("test-tiny-moe")
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(MeshConfig(expert=4, model=2))
    sharded = shard_params(params, mesh)
    assert sharded["blocks"]["w_gate"].sharding.spec == P(
        None, "expert", None, "model"
    )


# ---------------------------------------------------------------------------
# Ring attention
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seq_devices", [2, 4, 8])
def test_ring_attention_matches_causal(seq_devices):
    mesh = make_mesh(
        MeshConfig(data=8 // seq_devices, seq=seq_devices)
    )
    b, s, h, hkv, d = 2, 32, 4, 2, 8
    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(keys[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(keys[1], (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(keys[2], (b, s, hkv, d), jnp.float32)

    expected = causal_attention(q, k, v)
    got = ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=1e-4, atol=1e-4
    )


def test_ring_attention_rejects_ragged_seq():
    mesh = make_mesh(MeshConfig(data=2, seq=4))
    q = jnp.zeros((1, 30, 4, 8))
    with pytest.raises(ValueError):
        ring_attention_sharded(q, q[:, :, :2], q[:, :, :2], mesh)


def test_data_parallel_generate_across_mesh():
    """Candidate fan-out: batch sharded over `data` produces identical
    results to unsharded execution (the self-consistency DP axis)."""
    from llm_consensus_tpu.engine.generate import generate

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(MeshConfig(data=8))
    b = 8
    tokens = jnp.tile(jnp.array([[5, 9, 13]], jnp.int32), (b, 1))
    lengths = jnp.full((b,), 3, jnp.int32)

    baseline = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros(b), max_new_tokens=4,
    )
    sharded_tokens = jax.device_put(tokens, NamedSharding(mesh, P("data")))
    sharded_lengths = jax.device_put(lengths, NamedSharding(mesh, P("data")))
    replicated = shard_params(params, mesh)
    out = generate(
        cfg, replicated, sharded_tokens, sharded_lengths,
        jax.random.PRNGKey(0), jnp.zeros(b), max_new_tokens=4,
    )
    assert out.tokens.tolist() == baseline.tokens.tolist()


# ---------------------------------------------------------------------------
# Ring attention integrated into the model (VERDICT r2 #7)
# ---------------------------------------------------------------------------


def test_forward_with_ring_matches_dense_forward():
    """cfg.use_ring + a seq-sharded mesh routes model attention through
    the ring; logits must match the plain dense forward."""
    from llm_consensus_tpu.models.transformer import forward

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)

    want = forward(cfg, params, tokens)
    mesh = make_mesh(MeshConfig(data=2, seq=4))
    got = forward(cfg.with_(use_ring=True), params, tokens, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_engine_ring_prefill_matches_dense_generate():
    """Greedy generate with ring prefill over a seq-sharded mesh equals
    single-device generate token-for-token (long-context path live)."""
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    ecfg = EngineConfig(
        max_new_tokens=5, seq_buckets=(32,), batch_buckets=(1, 2)
    )
    plain = InferenceEngine(cfg, params, engine_config=ecfg)
    mesh = make_mesh(MeshConfig(data=2, seq=4))
    ring = InferenceEngine(
        cfg.with_(use_ring=True), params, engine_config=ecfg, mesh=mesh
    )
    prompts = ["the quick brown fox jumps", "pack my box"]
    want = [r.text for r in plain.generate_texts(prompts)]
    got = [r.text for r in ring.generate_texts(prompts)]
    assert got == want


def test_sharded_train_step_with_ring_matches_dense():
    """One sp-sharded train step with use_ring: loss equals the plain
    unsharded step's loss (same data, same init)."""
    from llm_consensus_tpu.training.train import (
        TrainConfig,
        init_train_state,
        make_sharded_train_step,
        make_train_step,
    )

    cfg = get_config("test-tiny")
    tcfg = TrainConfig(warmup_steps=1, total_steps=4, remat=False)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, 256)
    mask = jnp.ones((4, 32), jnp.float32)

    # Separate identically-initialized trees: the train steps donate
    # their state, deleting the first leg's buffers.
    params0 = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    step0 = make_train_step(cfg, tcfg)
    _, loss_plain = step0(
        init_train_state(cfg, params0, tcfg), tokens, mask
    )

    mesh = make_mesh(MeshConfig(data=2, seq=4))
    rcfg = cfg.with_(use_ring=True)
    params = init_params(rcfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    step1, place = make_sharded_train_step(rcfg, tcfg, mesh)
    state = init_train_state(rcfg, params, tcfg)
    state, s_tokens, s_mask = place(state, tokens, mask)
    _, loss_ring = step1(state, s_tokens, s_mask)
    np.testing.assert_allclose(
        float(loss_ring), float(loss_plain), rtol=1e-5
    )
