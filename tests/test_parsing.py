"""Unit tests for evaluation parsing (reference behavior src/main.rs:139-153)."""

from llm_consensus_tpu.consensus.messages import Feedback
from llm_consensus_tpu.consensus.parsing import parse_evaluation


def test_good_verdict():
    verdict, reasoning = parse_evaluation("Good\nBecause it is correct.")
    assert verdict is Feedback.GOOD
    assert reasoning == "Because it is correct."


def test_needs_refinement_verdict():
    verdict, reasoning = parse_evaluation("NeedsRefinement\nToo vague.")
    assert verdict is Feedback.NEEDS_REFINEMENT
    assert reasoning == "Too vague."


def test_spaces_stripped_from_verdict_line():
    # The reference removes ALL spaces from the first line (src/main.rs:142).
    verdict, _ = parse_evaluation("Needs Refinement\nreason")
    assert verdict is Feedback.NEEDS_REFINEMENT
    verdict, _ = parse_evaluation("  Good  \nreason")
    assert verdict is Feedback.GOOD


def test_leading_empty_lines_skipped():
    # Empty lines are filtered before taking the first (src/main.rs:139-141).
    verdict, reasoning = parse_evaluation("\n\nGood\nFine.")
    assert verdict is Feedback.GOOD
    assert reasoning == "Fine."


def test_unknown_verdict_counts_as_needs_refinement():
    # Quirk #4 (SURVEY.md §5): unparseable verdicts map to NeedsRefinement.
    verdict, _ = parse_evaluation("Excellent\nGreat answer!")
    assert verdict is Feedback.NEEDS_REFINEMENT


def test_empty_response_counts_as_needs_refinement():
    verdict, reasoning = parse_evaluation("")
    assert verdict is Feedback.NEEDS_REFINEMENT
    assert reasoning == ""


def test_multiline_reasoning_joined_with_blank_lines():
    # Reference joins remaining lines with "\n\n" (src/main.rs:143).
    verdict, reasoning = parse_evaluation("Good\nline one\nline two")
    assert reasoning == "line one\n\nline two"
