"""Persona registry tests (reference hard-codes its panel, src/main.rs:359-426)."""

from llm_consensus_tpu.consensus.personas import (
    Persona,
    default_panel,
    load_panel,
    save_panel,
)


def test_default_panel_matches_reference():
    panel = default_panel()
    assert [p.name for p in panel] == [
        "High Society",
        "The Technician",
        "Art Boy",
        "Programming Nerd",
    ]
    assert [p.domain for p in panel] == [
        "Society and Culture",
        "Technical Detail",
        "Art and Imagination",
        "Computer Science",
    ]
    for p in panel:
        assert p.tuning.count("*") == 10  # ten tuning bullets each


def test_panel_json_roundtrip(tmp_path):
    panel = default_panel() + [
        Persona("Judge", "Law", "* statutes", model="mistral-7b", weight=2.0)
    ]
    path = tmp_path / "panel.json"
    save_panel(panel, path)
    loaded = load_panel(path)
    assert loaded == panel
    assert loaded[-1].weight == 2.0
    assert loaded[-1].model == "mistral-7b"
