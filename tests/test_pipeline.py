"""Pipeline parallelism (parallel/pipeline.py) on the simulated mesh.

The reference has no model-parallel execution (SURVEY.md §2 "Parallelism
strategies — NOT PRESENT"); PP is part of the framework's scale-out
matrix (SURVEY.md §7 step 6). All tests run on the 8 simulated CPU
devices from conftest.
"""

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import forward, init_params
from llm_consensus_tpu.parallel.compat import SUPPORTS_PARTIAL_AUTO
from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh
from llm_consensus_tpu.parallel.pipeline import (
    make_pipeline_forward,
    make_pipeline_train_step,
    place_pipeline_params,
    pp_param_pspecs,
)
from llm_consensus_tpu.training.train import (
    TrainConfig,
    init_train_state,
    make_train_step,
)

CFG = get_config("test-tiny").with_(n_layers=4)
TCFG = TrainConfig(warmup_steps=1, total_steps=10, remat=True)


def _require_partial_auto(meshcfg: MeshConfig) -> None:
    """Skip when the mesh mixes manual (data/pipe) with auto (model)
    axes on a jax whose shard_map cannot express partial-auto — the
    old API's ``auto=`` lowering aborts XLA's partitioner outright
    (see ``parallel.compat.SUPPORTS_PARTIAL_AUTO``)."""
    if meshcfg.model > 1 and not SUPPORTS_PARTIAL_AUTO:
        pytest.skip("partial-auto shard_map unsupported on this jax")


def _batch(b=8, s=16, seed=1):
    tokens = jax.random.randint(
        jax.random.PRNGKey(seed), (b, s), 0, CFG.vocab_size
    )
    mask = jnp.ones((b, s), jnp.float32)
    return tokens, mask


def _params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


@pytest.mark.parametrize(
    "meshcfg,micro",
    [
        (MeshConfig(data=2, pipe=4), 2),
        (MeshConfig(pipe=4, model=2), 4),
        (MeshConfig(data=2, pipe=2, model=2), 2),
    ],
)
def test_pipeline_forward_matches_reference(cpu_devices, meshcfg, micro):
    """Pipelined logits == plain forward logits for dp/pp/tp combos."""
    _require_partial_auto(meshcfg)
    mesh = make_mesh(meshcfg, cpu_devices[: meshcfg.size])
    params = _params()
    tokens, _ = _batch()
    out = make_pipeline_forward(CFG, mesh, n_microbatches=micro)(
        place_pipeline_params(params, mesh), tokens
    )
    ref = forward(CFG, params, tokens)
    assert out.shape == ref.shape
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def test_pipeline_train_step_matches_unsharded(cpu_devices):
    """One GPipe train step == one unsharded train step (same init/batch)."""
    _require_partial_auto(MeshConfig(data=2, pipe=2, model=2))
    mesh = make_mesh(MeshConfig(data=2, pipe=2, model=2), cpu_devices)
    tokens, mask = _batch()

    step_u = make_train_step(CFG, TCFG)
    su, loss_u = step_u(init_train_state(CFG, _params(), TCFG), tokens, mask)

    pstep, place = make_pipeline_train_step(CFG, TCFG, mesh, n_microbatches=2)
    ps, ptok, pmask = place(
        init_train_state(CFG, _params(), TCFG), tokens, mask
    )
    ps2, loss_p = pstep(ps, ptok, pmask)

    assert abs(float(loss_u) - float(loss_p)) < 1e-4
    for a, b in zip(
        jax.tree_util.tree_leaves(su.params),
        jax.tree_util.tree_leaves(jax.device_get(ps2.params)),
    ):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_pipeline_loss_decreases(cpu_devices):
    """A few pipelined steps reduce the loss on a fixed batch."""
    mesh = make_mesh(MeshConfig(data=2, pipe=4), cpu_devices)
    tokens, mask = _batch()
    pstep, place = make_pipeline_train_step(CFG, TCFG, mesh, n_microbatches=4)
    state, ptok, pmask = place(
        init_train_state(CFG, _params(), TCFG), tokens, mask
    )
    losses = []
    for _ in range(4):
        state, loss = pstep(state, ptok, pmask)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_pp_param_pspecs_shard_layer_axis():
    """Block leaves get 'pipe' on the stacked layer axis; the rest of the
    tree keeps the TP rules."""
    specs = pp_param_pspecs(_params())
    assert specs["blocks"]["wq"][0] == "pipe"
    assert specs["blocks"]["wq"][2] == "model"
    assert specs["embed"][0] is None


def test_pipeline_rejects_indivisible_layers(cpu_devices):
    """L not divisible by n_stages fails fast at placement."""
    mesh = make_mesh(MeshConfig(pipe=8), cpu_devices)
    with pytest.raises(ValueError):
        place_pipeline_params(_params(), mesh)  # 4 layers, 8 stages
