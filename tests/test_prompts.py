"""Prompt-builder tests (reference src/main.rs:95,111-136,166-175)."""

from llm_consensus_tpu.consensus.personas import default_panel
from llm_consensus_tpu.consensus.prompts import (
    answer_prompt,
    evaluation_prompt,
    refinement_prompt,
)


def test_answer_prompt_shape():
    p = answer_prompt("What is 2+2?")
    assert p.startswith("Please answer the following question")
    assert "without referring to yourself as a language model" in p
    assert p.endswith("\n\nWhat is 2+2?")


def test_evaluation_prompt_contains_rubric_and_persona():
    persona = default_panel()[1]  # The Technician
    p = evaluation_prompt("Q?", "A.", persona)
    assert "Question: Q?" in p
    assert "Answer: A." in p
    assert persona.domain in p
    # Tuning bullets are quote-stripped but otherwise included.
    assert "Accuracy and precision of information" in p
    # Few-shot examples from the reference rubric.
    assert "What's a good beginner programming language?" in p
    assert "Decoupling" in p
    # Off-domain judges must abstain-approve (quirk #3).
    assert "you should answer exactly Good" in p


def test_prompts_strip_double_quotes():
    # Reference strips all '"' (src/main.rs:136,175) — quirk #7.
    persona = default_panel()[0]
    p = evaluation_prompt('He said "hi"', 'She replied "yo"', persona)
    assert '"' not in p
    r = refinement_prompt('Why "x"?', 'Because "y".', persona)
    assert '"' not in r


def test_refinement_prompt_mentions_domain_and_tuning():
    persona = default_panel()[3]
    p = refinement_prompt("Q?", "A.", persona)
    assert "you said it needed refinement" in p
    assert persona.domain in p
    assert "Algorithms and data structures" in p
