"""Weight-only int8 quantization (ops/quant.py).

The reference has no local compute to quantize (its model is a remote
API, ``src/main.rs:82-86``); quantization is part of this framework's
own decode-throughput work (BASELINE.json north-star floor).
"""

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.engine.generate import generate
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import forward, init_params
from llm_consensus_tpu.ops.quant import (
    QuantizedTensor,
    dequantize,
    quantize_params,
    quantize_tensor,
    quantized_bytes,
)


def test_quantize_roundtrip_error_bound():
    """Per-channel symmetric int8: reconstruction error <= scale/2 + eps."""
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    qt = quantize_tensor(w, axis=0)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (1, 128)
    err = jnp.abs(dequantize(qt, jnp.float32) - w)
    assert float(jnp.max(err - qt.scale / 2)) < 1e-6


@pytest.mark.parametrize("preset", ["test-tiny", "test-tiny-moe"])
def test_quantized_forward_close(preset):
    """Quantized logits stay close to the full-precision logits."""
    cfg = get_config(preset)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qp = quantize_params(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size
    )
    ref = forward(cfg, params, tokens)
    out = forward(cfg, qp, tokens)
    rel = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    assert rel < 0.05


def test_quantized_params_shrink_and_skip_small_leaves():
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qp = quantize_params(params)
    assert quantized_bytes(qp) < 0.5 * quantized_bytes(params)
    # Matmul weights quantized; norms/embed untouched; idempotent.
    assert isinstance(qp["blocks"]["wq"], QuantizedTensor)
    assert isinstance(qp["blocks"]["w_down"], QuantizedTensor)
    assert not isinstance(qp["blocks"]["attn_norm"], QuantizedTensor)
    assert not isinstance(qp["embed"], QuantizedTensor)
    qp2 = quantize_params(qp)
    assert qp2["blocks"]["wq"] is qp["blocks"]["wq"]


def test_quantized_generate_runs():
    """The jitted generate loop accepts a quantized param tree."""
    cfg = get_config("test-tiny")
    params = quantize_params(
        init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    )
    tokens = jnp.ones((2, 8), jnp.int32)
    out = generate(
        cfg,
        params,
        tokens,
        jnp.full((2,), 8, jnp.int32),
        jax.random.PRNGKey(0),
        jnp.zeros((2,), jnp.float32),
        max_new_tokens=4,
    )
    assert out.tokens.shape == (2, 4)


def test_quantized_params_shard_tensor_parallel(cpu_devices):
    """int8 scales (size-1 contraction dim) must replicate, not inherit
    the row-parallel spec — TP sharding of quantized params must work."""
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh
    from llm_consensus_tpu.parallel.partitioning import shard_params

    cfg = get_config("test-tiny")
    qp = quantize_params(
        init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    )
    mesh = make_mesh(MeshConfig(data=2, model=4), cpu_devices)
    sharded = shard_params(qp, mesh)
    wo = sharded["blocks"]["wo"]
    assert wo.q.sharding.spec == ("model",) or wo.q.sharding.spec[1] == "model"
    assert "model" not in tuple(wo.scale.sharding.spec)
    tokens = jnp.ones((2, 8), jnp.int32)
    out = forward(cfg, sharded, tokens)
    assert out.shape == (2, 8, cfg.vocab_size)


def test_quant_matmul_kernel_matches_fallback():
    """ops.quant.matmul: Pallas int8 kernel (interpret) == XLA dequant."""
    from llm_consensus_tpu.ops import quant as quant_mod
    from llm_consensus_tpu.ops.pallas.quant_matmul import (
        quant_matmul_supported,
    )

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 1, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (256, 384), jnp.float32)
    qt = quantize_tensor(w, axis=0)
    assert quant_matmul_supported(2, 256, 384)
    ref = quant_mod.matmul(x, qt)  # CPU default: XLA fallback
    quant_mod._FORCE_KERNEL = True
    try:
        out = quant_mod.matmul(x, qt)
    finally:
        quant_mod._FORCE_KERNEL = None
    assert out.shape == ref.shape == (2, 1, 384)
    rel = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    assert rel < 0.02


def test_quantized_decode_with_kernel_matches_xla_path():
    """End-to-end decode step with the kernel forced on (interpret)."""
    from llm_consensus_tpu.models.cache import KVCache
    from llm_consensus_tpu.models.transformer import decode_step, prefill
    from llm_consensus_tpu.ops import quant as quant_mod

    cfg = get_config("test-tiny")  # d_model=64 < 128: unsupported shapes
    cfg = cfg.with_(d_model=128, n_heads=4, n_kv_heads=2, d_ff=256)
    params = quantize_params(
        init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    )
    tokens = jnp.ones((2, 8), jnp.int32)
    lengths = jnp.full((2,), 8, jnp.int32)

    def run():
        cache = KVCache.create(cfg, 2, 16, dtype=jnp.float32)
        logits, cache = prefill(cfg, params, tokens, lengths, cache)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        logits2, _ = decode_step(cfg, params, tok[:, None], cache)
        return logits2

    ref = run()
    quant_mod._FORCE_KERNEL = True
    try:
        out = run()
    finally:
        quant_mod._FORCE_KERNEL = None
    rel = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    assert rel < 0.05


def test_engine_quant_config():
    """EngineConfig(quant='int8') quantizes at init; bad mode rejected."""
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = InferenceEngine(
        cfg, params, engine_config=EngineConfig(quant="int8")
    )
    assert isinstance(eng.params["blocks"]["wq"], QuantizedTensor)
    results = eng.generate_texts(["hello"], max_new_tokens=4)
    assert len(results) == 1 and isinstance(results[0].text, str)
    with pytest.raises(ValueError):
        InferenceEngine(cfg, params, engine_config=EngineConfig(quant="fp4"))


# ---------------------------------------------------------------------------
# int4 (packed nibbles)
# ---------------------------------------------------------------------------


def test_int4_pack_unpack_exact():
    """Values on the int4 grid survive pack -> unpack exactly."""
    from llm_consensus_tpu.ops.quant import quantize_tensor4, unpack4

    w = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    qt = quantize_tensor4(w, axis=0)
    assert qt.q.shape == (32, 128)  # contraction dim halved
    assert qt.shape == (64, 128)  # logical shape
    grid = unpack4(qt.q, jnp.float32)
    assert float(jnp.min(grid)) >= -8 and float(jnp.max(grid)) <= 7
    # Re-quantizing the dequantized weight reproduces the same nibbles.
    from llm_consensus_tpu.ops.quant import dequantize4

    qt2 = quantize_tensor4(dequantize4(qt, jnp.float32), axis=0)
    assert jnp.array_equal(qt.q, qt2.q)


def test_int4_roundtrip_error_bound():
    from llm_consensus_tpu.ops.quant import dequantize4, quantize_tensor4

    w = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    qt = quantize_tensor4(w, axis=0)
    err = jnp.abs(dequantize4(qt, jnp.float32) - w)
    assert float(jnp.max(err - qt.scale / 2)) < 1e-6


def test_int4_rejects_bad_axis_or_odd_dim():
    from llm_consensus_tpu.ops.quant import quantize_tensor4

    w = jnp.zeros((64, 128))
    with pytest.raises(ValueError, match="axis -2"):
        quantize_tensor4(w, axis=1)
    with pytest.raises(ValueError, match="even"):
        quantize_tensor4(jnp.zeros((63, 128)), axis=0)


def test_quant4_matmul_kernel_matches_dequant():
    """Fused int4 kernel (interpret) == unpack + XLA dot."""
    from llm_consensus_tpu.ops.pallas.quant_matmul import (
        quant4_matmul_2d,
        quant4_matmul_supported,
    )
    from llm_consensus_tpu.ops.quant import dequantize4, quantize_tensor4

    k, n, m = 256, 384, 8
    w = jax.random.normal(jax.random.PRNGKey(0), (k, n), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (m, k), jnp.bfloat16)
    qt = quantize_tensor4(w, axis=0)
    assert quant4_matmul_supported(m, k, n)
    got = quant4_matmul_2d(x, qt.q, qt.scale, interpret=True)
    want = (x.astype(jnp.float32) @ dequantize4(qt, jnp.float32)).astype(
        jnp.bfloat16
    )
    assert float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32)))) < 0.5


@pytest.mark.parametrize("preset", ["test-tiny", "test-tiny-moe"])
def test_int4_forward_close(preset):
    """int4 logits stay reasonably close to full precision (coarser grid
    than int8, so a looser bound)."""
    from llm_consensus_tpu.ops.quant import Quantized4Tensor

    cfg = get_config(preset)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qp = quantize_params(params, bits=4)
    assert isinstance(qp["blocks"]["wq"], Quantized4Tensor)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size
    )
    ref = forward(cfg, params, tokens)
    out = forward(cfg, qp, tokens)
    rel = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    assert rel < 0.35


def test_int4_bytes_half_of_int8():
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    q8 = quantize_params(params, bits=8)
    q4 = quantize_params(params, bits=4)

    def block_bytes(p):
        return sum(
            leaf.size * leaf.dtype.itemsize
            for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
            for leaf in [getattr(p["blocks"][name], "q")]
        )

    assert block_bytes(q4) == block_bytes(q8) // 2


def test_int4_engine_generates():
    """End-to-end: the engine decodes with int4 weights."""
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = InferenceEngine(
        cfg,
        params,
        engine_config=EngineConfig(
            max_new_tokens=4, seq_buckets=(16,), batch_buckets=(1, 2),
            quant="int4",
        ),
    )
    out = eng.generate_texts(["hello", "world"])
    assert len(out) == 2
    assert all(r.num_tokens >= 1 for r in out)


def test_int4_params_shard_on_mesh():
    """Packed int4 leaves place under the same partitioning rules."""
    from jax.sharding import PartitionSpec as P

    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh
    from llm_consensus_tpu.parallel.partitioning import shard_params

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    qp = quantize_params(params, bits=4)
    mesh = make_mesh(MeshConfig(data=2, model=2, expert=2))
    sharded = shard_params(qp, mesh)
    assert sharded["blocks"]["wq"].q.sharding.spec == P(None, None, "model")


def test_stacked_kernel_matches_per_layer_slice():
    """quant_matmul_stacked(x, stack, l) == quant_matmul_2d(x, stack[l])."""
    import numpy as np

    from llm_consensus_tpu.ops.pallas.quant_matmul import (
        quant_matmul_2d,
        quant_matmul_stacked,
    )

    key = jax.random.PRNGKey(0)
    n_layers, m, k, n = 3, 8, 128, 256
    w = jax.random.randint(key, (n_layers, k, n), -127, 127, jnp.int8)
    s = jnp.abs(jax.random.normal(key, (n_layers, 1, n), jnp.float32)) * 0.02
    x = jax.random.normal(jax.random.fold_in(key, 1), (m, k), jnp.bfloat16)
    for layer in range(n_layers):
        want = quant_matmul_2d(x, w[layer], s[layer], interpret=True)
        got = quant_matmul_stacked(
            x, w, s, jnp.asarray(layer), interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got, np.float32),
            np.asarray(want, np.float32),
            rtol=1e-2,
            atol=1e-2,
        )


def test_matmul_stacked_quant_view_matches_sliced():
    """ops.quant.matmul on a StackedQuant view == matmul on the slice,
    with the kernel both forced on and forced off."""
    import numpy as np

    from llm_consensus_tpu.ops.quant import (
        StackedQuant,
        matmul,
        quantize_tensor,
        set_kernel_enabled,
    )

    key = jax.random.PRNGKey(2)
    stack = jax.random.normal(key, (2, 128, 256), jnp.float32)
    qt = quantize_tensor(stack, axis=1)  # [2,128,256] int8, [2,1,256] scale
    x = jax.random.normal(jax.random.fold_in(key, 3), (4, 128), jnp.bfloat16)
    from llm_consensus_tpu.ops.quant import QuantizedTensor

    for force in (True, False):
        set_kernel_enabled(force)
        try:
            for layer in range(2):
                sliced = QuantizedTensor(
                    q=qt.q[layer], scale=qt.scale[layer]
                )
                want = matmul(x, sliced)
                got = matmul(
                    x, StackedQuant(full=qt, layer=jnp.asarray(layer))
                )
                np.testing.assert_allclose(
                    np.asarray(got, np.float32),
                    np.asarray(want, np.float32),
                    rtol=2e-2,
                    atol=2e-2,
                )
        finally:
            set_kernel_enabled(None)
