"""One ragged paged attention kernel + the fused scheduler step (PR 8).

Two layers of contract:

- KERNEL: :func:`llm_consensus_tpu.ops.pallas.ragged_paged_attention`
  serves mixed decode + prefill-chunk rows, shared-prefix groups, and
  sliding windows in ONE program, parity-checked against the XLA
  reference (`ops.attention.ragged_paged_attention_reference`) across
  the ragged shapes: mid-block lengths, MQA, degenerate one-member
  groups, all-decode, all-prefill, int8 KV (head-major AND stacked).
- BATCHER: with ``ContinuousConfig.ragged_attention`` (default on) a
  ready prefill chunk rides the decode dispatch as one more ragged
  row — ONE device program per scheduler iteration — with generated
  text byte-identical to the split-program path across pipeline
  depths, chunk widths, stops landing mid-flight, eviction +
  host-restore in flight, and sliding-window configs (which used to
  fall back out of the grouped kernel entirely).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.ops.attention import (
    decode_attention_shared_prefix_quant,
    ragged_paged_attention_reference,
)
from llm_consensus_tpu.ops.pallas.attention import (
    flash_decode_attention_shared_prefix_q8_stacked,
    ragged_paged_attention,
)
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)

CFG = get_config("test-tiny")

_CCFG = dict(
    max_slots=4,
    page_size=16,
    n_pages=96,
    pages_per_seq=8,
    max_new_tokens=8,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


# ---------------------------------------------------------------------------
# Kernel vs XLA reference (CPU interpret)
# ---------------------------------------------------------------------------


def _pool(rng, n_pages=40, pg=8, hkv=2, d=32):
    k = jnp.asarray(rng.standard_normal((n_pages, pg, hkv, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((n_pages, pg, hkv, d)), jnp.bfloat16)
    return k, v


def _check(got, want, rtol=2e-2, atol=2e-2):
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=rtol, atol=atol,
    )


@pytest.mark.parametrize("window", [0, 9])
def test_ragged_mixed_rows_match_reference(window):
    """Decode rows at mid-block lengths + one chunk row, one program."""
    rng = np.random.default_rng(0)
    pg, hkv, d, g, b, p_per, cq = 8, 2, 32, 3, 4, 6, 16
    h = hkv * g
    kp, vp = _pool(rng, pg=pg, hkv=hkv, d=d)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.bfloat16)
    qc = jnp.asarray(rng.standard_normal((cq, h, d)), jnp.bfloat16)
    perm = rng.permutation(np.arange(1, 40))
    tbl = jnp.asarray(perm[: b * p_per].reshape(b, p_per), jnp.int32)
    ctbl = jnp.asarray(perm[b * p_per : b * p_per + p_per], jnp.int32)
    vl = jnp.asarray([13, 1, 40, 23], jnp.int32)  # mid-block fills
    cstart = jnp.int32(11)  # chunk starts mid-block too
    got_d, got_c = ragged_paged_attention(
        q, kp, vp, tbl, vl, q_chunk=qc, chunk_table=ctbl,
        chunk_start=cstart, window=window, interpret=True,
    )
    ref_d, ref_c = ragged_paged_attention_reference(
        q, kp, vp, tbl, vl, q_chunk=qc, chunk_table=ctbl,
        chunk_start=cstart, window=window,
    )
    _check(got_d, ref_d)
    _check(got_c, ref_c)


def test_ragged_mqa_single_kv_head():
    rng = np.random.default_rng(1)
    pg, hkv, d, g, b, p_per = 8, 1, 32, 4, 3, 4
    kp, vp = _pool(rng, pg=pg, hkv=hkv, d=d)
    q = jnp.asarray(rng.standard_normal((b, hkv * g, d)), jnp.bfloat16)
    tbl = jnp.asarray(
        rng.permutation(np.arange(1, 40))[: b * p_per].reshape(b, p_per),
        jnp.int32,
    )
    vl = jnp.asarray([7, 30, 12], jnp.int32)
    got = ragged_paged_attention(q, kp, vp, tbl, vl, interpret=True)
    ref = ragged_paged_attention_reference(q, kp, vp, tbl, vl)
    _check(got, ref)


@pytest.mark.parametrize("window", [0, 9])
def test_ragged_grouped_rows_with_chunk(window):
    """Groups + ungrouped rows + a chunk lane in the same program —
    grouping is a bandwidth optimization, output must equal the
    ungrouped reference (including under a sliding window, the config
    that used to fall back)."""
    rng = np.random.default_rng(2)
    pg, hkv, d, g, b, p_per, cq = 8, 2, 32, 3, 4, 6, 16
    h = hkv * g
    kp, vp = _pool(rng, pg=pg, hkv=hkv, d=d)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.bfloat16)
    qc = jnp.asarray(rng.standard_normal((cq, h, d)), jnp.bfloat16)
    perm = rng.permutation(np.arange(1, 40))
    tbl = np.asarray(perm[: b * p_per].reshape(b, p_per), np.int32)
    # Rows 0, 2, 3 share their first page (same tokens by construction).
    tbl[2, 0] = tbl[0, 0]
    tbl[3, 0] = tbl[0, 0]
    tbl = jnp.asarray(tbl)
    ctbl = jnp.asarray(perm[b * p_per : b * p_per + p_per], jnp.int32)
    vl = jnp.asarray([13, 9, 40, 23], jnp.int32)
    groups = (
        jnp.asarray([0, -1, 0, 0], jnp.int32),  # group_id
        jnp.asarray([0], jnp.int32),  # rep
        jnp.asarray([pg], jnp.int32),  # group_end (tokens)
        jnp.asarray([pg, 0, pg, pg], jnp.int32),  # shared_start
    )
    got_d, got_c = ragged_paged_attention(
        q, kp, vp, tbl, vl, q_chunk=qc, chunk_table=ctbl,
        chunk_start=jnp.int32(11), groups=groups, window=window,
        interpret=True,
    )
    ref_d, ref_c = ragged_paged_attention_reference(
        q, kp, vp, tbl, vl, q_chunk=qc, chunk_table=ctbl,
        chunk_start=jnp.int32(11), window=window,
    )
    _check(got_d, ref_d)
    _check(got_c, ref_c)


def test_ragged_degenerate_single_member_group():
    """A one-member group must not change that row's output (the
    tracker never emits one, but the kernel tolerates it)."""
    rng = np.random.default_rng(3)
    pg, hkv, d, g, b, p_per = 8, 2, 32, 2, 3, 4
    kp, vp = _pool(rng, pg=pg, hkv=hkv, d=d)
    q = jnp.asarray(rng.standard_normal((b, hkv * g, d)), jnp.bfloat16)
    tbl = jnp.asarray(
        rng.permutation(np.arange(1, 40))[: b * p_per].reshape(b, p_per),
        jnp.int32,
    )
    vl = jnp.asarray([20, 11, 30], jnp.int32)
    groups = (
        jnp.asarray([-1, 0, -1], jnp.int32),
        jnp.asarray([1], jnp.int32),
        jnp.asarray([pg], jnp.int32),
        jnp.asarray([0, pg, 0], jnp.int32),
    )
    got = ragged_paged_attention(
        q, kp, vp, tbl, vl, groups=groups, interpret=True
    )
    ref = ragged_paged_attention_reference(q, kp, vp, tbl, vl)
    _check(got, ref)


def test_ragged_all_prefill_and_dead_decode_rows():
    """kv_len 0 decode rows (an idle batcher's slots) stay finite while
    the chunk row — the only live work — still matches the reference."""
    rng = np.random.default_rng(4)
    pg, hkv, d, g, b, p_per, cq = 8, 2, 32, 2, 3, 4, 8
    h = hkv * g
    kp, vp = _pool(rng, pg=pg, hkv=hkv, d=d)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.bfloat16)
    qc = jnp.asarray(rng.standard_normal((cq, h, d)), jnp.bfloat16)
    perm = rng.permutation(np.arange(1, 40))
    tbl = jnp.zeros((b, p_per), jnp.int32)  # all-NULL tables
    ctbl = jnp.asarray(perm[:p_per], jnp.int32)
    vl = jnp.zeros((b,), jnp.int32)
    got_d, got_c = ragged_paged_attention(
        q, kp, vp, tbl, vl, q_chunk=qc, chunk_table=ctbl,
        chunk_start=jnp.int32(0), interpret=True,
    )
    _, ref_c = ragged_paged_attention_reference(
        q, kp, vp, tbl, vl, q_chunk=qc, chunk_table=ctbl,
        chunk_start=jnp.int32(0),
    )
    _check(got_c, ref_c)
    assert np.isfinite(np.asarray(got_d, np.float32)).all()


def test_ragged_stacked_q8_shared_prefix_matches_reference():
    """The stacked int8 cache case that used to FALL BACK to the
    ungrouped stacked kernel: shared-prefix attention through the
    ragged kernel's stacked layout, vs the dequantizing reference."""
    rng = np.random.default_rng(5)
    L, b, hkv, s_len, d, g = 3, 4, 2, 64, 32, 3
    h = hkv * g
    kq = jnp.asarray(rng.integers(-127, 127, (L, b, hkv, s_len, d)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 127, (L, b, hkv, s_len, d)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.01, 0.02, (L, b, hkv, s_len)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.02, (L, b, hkv, s_len)), jnp.float32)
    plen = 21  # mid-block boundary
    kq = kq.at[:, :, :, :plen].set(
        jnp.broadcast_to(kq[:, :1, :, :plen], (L, b, hkv, plen, d))
    )
    vq = vq.at[:, :, :, :plen].set(
        jnp.broadcast_to(vq[:, :1, :, :plen], (L, b, hkv, plen, d))
    )
    ks = ks.at[:, :, :, :plen].set(
        jnp.broadcast_to(ks[:, :1, :, :plen], (L, b, hkv, plen))
    )
    vs = vs.at[:, :, :, :plen].set(
        jnp.broadcast_to(vs[:, :1, :, :plen], (L, b, hkv, plen))
    )
    q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.bfloat16)
    vl = jnp.asarray([30, 25, 64, 40], jnp.int32)
    layer = jnp.int32(1)
    got = flash_decode_attention_shared_prefix_q8_stacked(
        q, kq, ks, vq, vs, vl, jnp.int32(plen), layer, interpret=True
    )
    ref = decode_attention_shared_prefix_quant(
        q, kq[1], ks[1], vq[1], vs[1], vl, jnp.int32(plen)
    )
    _check(got, ref)


# ---------------------------------------------------------------------------
# Batcher: the fused scheduler step
# ---------------------------------------------------------------------------

_HEADER = "Panel shared header for every persona, forty ch: "


def _serve(batcher, prompts, **kw):
    futs = [batcher.submit(p, **kw) for p in prompts]
    return [f.result(timeout=120) for f in futs]


def _quiesce(batcher, timeout=10.0):
    """Wait for the scheduler loop to go fully idle: futures resolve
    at fetch time, but the loop can still be draining in-flight
    programs/overshoot — counter reads across that tail would smear
    iterations between measurement windows."""
    import time

    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        s = batcher.stats()
        if (
            s["active_slots"] == 0
            and s["prefilling_slots"] == 0
            and s["dispatch_inflight"] == 0
            and s["waiting"] == 0
        ):
            return s
        time.sleep(0.01)
    return batcher.stats()


def _burst_texts(params, ragged, depth=2, chunk=16, cfg=CFG, cfgkw=None,
                 prompts=None, **submit_kw):
    ccfg = dict(_CCFG, prefill_chunk=chunk)
    ccfg.update(cfgkw or {})
    b = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(
            **ccfg, pipeline_depth=depth, ragged_attention=ragged
        ),
    )
    prompts = prompts or [
        _HEADER + "alpha tail one",
        _HEADER + "beta tail two",
        "unrelated prompt entirely",
        _HEADER + "gamma tail three",
    ]
    try:
        return [r.text for r in _serve(b, prompts, **submit_kw)], b.stats()
    finally:
        b.close()


def test_fused_text_parity_across_depths_and_chunks(params):
    """THE acceptance contract: generated text is byte-identical with
    the fused scheduler step on vs off, across pipeline depths {1, 2}
    and prefill-chunk widths — the fused program is a pure
    restructuring of the same math."""
    want, _ = _burst_texts(params, ragged=False, depth=1, chunk=16)
    for depth in (1, 2):
        for chunk in (16, 32):
            for ragged in (True, False):
                got, _ = _burst_texts(
                    params, ragged=ragged, depth=depth, chunk=chunk
                )
                assert got == want, (ragged, depth, chunk)


def test_fused_stop_mid_chunk_parity(params):
    """A multi-token string stop landing while a later request's chunk
    rides the pipeline: stop-trim and retirement must stay
    byte-identical to the split-program path."""
    prompts = [_HEADER + "one", _HEADER + "two", _HEADER + "three"]
    kw = dict(prompts=prompts, temperature=0.9, seed=3, stop=["\x00", "ab"])
    want, _ = _burst_texts(params, ragged=False, depth=1, **kw)
    for ragged, depth in ((True, 1), (True, 2), (False, 2)):
        got, _ = _burst_texts(params, ragged=ragged, depth=depth, **kw)
        assert got == want, (ragged, depth)


def test_fused_sliding_window_config_parity(params):
    """Sliding-window configs used to fall back out of the grouped
    kernel AND the fused path did not exist; now both ride the same
    ragged program — text parity on a windowed model config."""
    wcfg = CFG.with_(sliding_window=24)
    want, _ = _burst_texts(params, ragged=False, depth=1, cfg=wcfg)
    for ragged, depth in ((True, 1), (True, 2)):
        got, _ = _burst_texts(params, ragged=ragged, depth=depth, cfg=wcfg)
        assert got == want, (ragged, depth)


def test_fused_eviction_and_host_restore_in_flight(params):
    """Host-tier demote/restore (flush-first stable-cache operations)
    interleaved with fused dispatches: text parity holds and the tier
    still engages. Pool sized so the second round's header must come
    back from the host store."""
    cfgkw = dict(
        max_slots=2,
        page_size=16,
        n_pages=13,  # 12 usable vs a 2x6-page unshared working set
        pages_per_seq=8,
        max_new_tokens=6,
        seq_buckets=(16, 32, 64),
        prefill_chunk=16,
        share_prefix=True,
        host_cache_bytes=8 << 20,
    )
    rounds = [
        [_HEADER + f"p{i} proposes" for i in range(2)],
        [
            f"{i} unique filler storm with plenty of padding text {i}"
            for i in range(4)
        ],
        [_HEADER + f"r{i} re-votes" for i in range(2)],
    ]

    def run(ragged):
        b = ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(**cfgkw, ragged_attention=ragged),
        )
        try:
            texts = []
            for r in rounds:
                texts.append([x.text for x in _serve(b, r)])
            return texts, b.stats()
        finally:
            b.close()

    want, st_off = run(False)
    got, st_on = run(True)
    assert got == want
    assert st_on["offload_restored_pages"] >= 1
    assert st_on["offload_restored_pages"] == st_off["offload_restored_pages"]


def test_device_programs_one_per_iteration_and_metrics_lockstep(params):
    """Fused leg: every scheduler iteration that ran device work ran
    exactly ONE program; unfused leg: chunk+decode iterations ran two.
    The Prometheus families move by the batcher's own deltas."""
    from llm_consensus_tpu.server.metrics import DEVICE_PROGRAMS, RAGGED_ROWS

    prompts = [_HEADER + f"req {i}" for i in range(6)] + [
        f"unique header {i} " * 4 for i in range(6)
    ]

    def run(ragged):
        before = {
            k: DEVICE_PROGRAMS.labels(kind=k).value
            for k in ("fused", "decode", "prefill")
        }
        rows0 = (RAGGED_ROWS.sum, RAGGED_ROWS.count)
        b = ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(**_CCFG, ragged_attention=ragged),
        )
        try:
            texts = [r.text for r in _serve(b, prompts)]
            st = _quiesce(b)
        finally:
            b.close()
        for k in ("fused", "decode", "prefill"):
            assert (
                DEVICE_PROGRAMS.labels(kind=k).value - before[k]
                == st[f"device_programs_{k}"]
            )
        assert RAGGED_ROWS.sum - rows0[0] == st["ragged_rows_sum"]
        assert RAGGED_ROWS.count - rows0[1] == st["ragged_rows_count"]
        return texts, st

    texts_on, st_on = run(True)
    texts_off, st_off = run(False)
    assert texts_on == texts_off
    on_programs = sum(
        st_on[f"device_programs_{k}"] for k in ("fused", "decode", "prefill")
    )
    off_programs = sum(
        st_off[f"device_programs_{k}"] for k in ("fused", "decode", "prefill")
    )
    # Fusion engaged and collapsed chunk+decode iterations to ONE
    # program; the old path needed more programs than iterations.
    assert st_on["device_programs_fused"] >= 1
    assert on_programs == st_on["work_iterations"]
    assert off_programs > st_off["work_iterations"]
    assert st_off["device_programs_fused"] == 0
    # Ragged-row occupancy counts decode rows + the fused chunk lane.
    assert st_on["ragged_rows_count"] >= st_on["device_programs_fused"]


def test_prefill_chunks_and_stall_lockstep_under_fusion(params):
    """Fused chunks observe a 0 stall (they ride the dispatch) but the
    histogram count stays in lockstep with ``prefill_chunks`` — the
    PR 2 contract survives the fusion."""
    from llm_consensus_tpu.server.metrics import PREFILL_STALL_SECONDS

    before = PREFILL_STALL_SECONDS.count
    _, st = _burst_texts(params, ragged=True)
    assert st["prefill_chunks"] >= 2
    assert PREFILL_STALL_SECONDS.count - before == st["prefill_chunks"]
