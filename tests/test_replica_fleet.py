"""Prefix-affinity replica fleet (PR 14).

Covers the :class:`~llm_consensus_tpu.serving.fleet.ReplicaSet` /
:class:`PrefixRouter` subsystem end to end: affinity routing lands a
panel's mates on the donor's replica (the shared header prefills once
FLEET-wide), the preempt→demote→re-admit round trip is byte-identical,
rebalancing exports a chain through the fleet-shared
:class:`HostPageStore` and the next hit restores it on another replica,
the shared store stays correct under concurrent demotes from two
replicas (the PR-14 lock audit), scoped keys keep heterogeneous
replicas from cross-restoring, the gateway's ``/readyz`` aggregates
per-replica heartbeats (one wedged replica flips readiness, reported by
index, and the router stops routing to it), metrics/stats move in
lockstep, and the ``bench.py --serve-replicas`` CPU A/B leg gates
affinity hit rate above the random-routing control with a zero-429
overload storm.
"""

import json
import re
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.paged_cache import (
    PagePool,
    PrefixRegistry,
    prefix_chain_key,
)
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.server.metrics import REGISTRY
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)
from llm_consensus_tpu.serving.fleet import (
    FleetBackend,
    FleetConfig,
    ReplicaSet,
)
from llm_consensus_tpu.serving.offload import HostPageStore

CFG = get_config("test-tiny")

# 49 chars -> 3 full 16-token pages + a tail at page_size 16.
_HEADER = "Panel shared header for every persona, forty ch: "

# Small enough to stay fast, big enough for 2 replicas to serve
# concurrently; the preempt test overrides n_pages to starve the pool.
_FCFG = dict(
    max_slots=2,
    page_size=16,
    n_pages=32,
    pages_per_seq=8,
    max_new_tokens=4,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
    host_cache_bytes=64 << 20,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _serve(target, prompts, **kw):
    futs = [target.submit(p, **kw) for p in prompts]
    return [f.result(timeout=300).text for f in futs]


def _fleet(params, replicas=2, fleet_kw=None, **cfg_over):
    return ReplicaSet(
        CFG,
        params,
        config=ContinuousConfig(**{**_FCFG, **cfg_over}),
        fleet=FleetConfig(replicas=replicas, **(fleet_kw or {})),
    )


# ---------------------------------------------------------------------------
# Chain fingerprint + read-only probe (the router's primitives)
# ---------------------------------------------------------------------------


def test_prefix_chain_key_matches_registry_identity():
    ids = list(range(100, 135))  # 35 tokens, page 16
    chain = prefix_chain_key(ids, 16)
    # (35 - 1) // 16 = 2 usable full pages — the last token always
    # recomputes, exactly the registry's match cap.
    assert len(chain) == 2
    assert chain[0] == tuple(range(100, 116))
    assert chain[1] == tuple(range(116, 132))
    # 33 tokens: the 2nd page's last token would be the prompt's last.
    assert len(prefix_chain_key(ids[:33], 16)) == 2
    assert len(prefix_chain_key(ids[:32], 16)) == 1
    assert prefix_chain_key(ids[:16], 16) == ()


def test_registry_probe_is_read_only():
    pool = PagePool(range(1, 16))
    reg = PrefixRegistry(pool, 4)
    ids = list(range(50, 62))  # 2 usable full pages + tail
    pages = pool.alloc(2)
    created = reg.register(ids, pages)
    rc_before = {p: pool.refcount(p) for p in pages}
    lookups, hits = reg.lookups, reg.hits
    nodes, tokens = reg.probe(ids)
    assert tokens == 8 and len(nodes) == 2
    # NO side effects: refcounts, counters, and LRU ticks untouched.
    assert {p: pool.refcount(p) for p in pages} == rc_before
    assert (reg.lookups, reg.hits) == (lookups, hits)
    # Unready nodes count (burst mates probe an in-flight prefill).
    assert not created[0][0].ready
    # A diverging prompt stops at the divergence page.
    other = ids[:4] + [999] * 8
    _, t2 = reg.probe(other)
    assert t2 == 4


# ---------------------------------------------------------------------------
# Shared store: concurrency (the PR-14 lock audit) + scoped keys
# ---------------------------------------------------------------------------


def test_store_touch_reports_lost_race():
    store = HostPageStore(budget_bytes=1 << 20)
    planes = (np.ones((4, 8), np.float32),)
    assert store.put(("a",), planes)
    assert store.touch(("a",)) is True
    assert store.touch(("gone",)) is False  # caller must re-fetch+put


def test_store_concurrent_demote_accounting_stays_exact():
    """Two 'replicas' demote overlapping chain sets concurrently; the
    byte accounting, LRU order, and per-call deltas must stay exact
    under interleaving (put_counted returns THIS call's deltas — the
    caller never reconstructs them from global counters)."""
    page = 128  # 4*8 float32
    store = HostPageStore(budget_bytes=64 * page)
    demoted = [0, 0]
    dropped = [0, 0]
    errs = []

    def replica(idx):
        try:
            rng = np.random.default_rng(idx)
            for round_ in range(40):
                for c in range(32):
                    key = ("chain", c % 24)  # overlapping key space
                    if not store.touch(key):
                        planes = (
                            rng.standard_normal((4, 8)).astype(np.float32),
                        )
                        _, d, dr = store.put_counted(key, planes)
                        demoted[idx] += d
                        dropped[idx] += dr
                    else:
                        demoted[idx] += 1
                    store.get(("chain", (c * 7) % 24))
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=replica, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    # Exact invariants after arbitrary interleaving: resident bytes
    # match the entries, per-call deltas sum to the global counters.
    assert store.bytes_used == len(store) * page
    assert store.bytes_used <= store.budget_bytes
    assert store.demoted_pages == demoted[0] + demoted[1]
    assert store.dropped_pages == dropped[0] + dropped[1]
    assert len(store) <= 24


def test_store_scope_blocks_heterogeneous_cross_restore(params):
    """Two batchers with DIFFERENT weights share one store: the second
    must never restore the first's pages (a page's bytes are a
    function of the weights that wrote it) — store keys carry the
    config+weights scope, so B's probe misses A's entries."""
    params_b = init_params(CFG, jax.random.PRNGKey(1), dtype=jnp.float32)
    store = HostPageStore(budget_bytes=64 << 20)
    prompts = [_HEADER + f"q{i}" for i in range(2)]
    a = ContinuousBatcher(
        CFG, params, config=ContinuousConfig(**_FCFG), host_store=store
    )
    try:
        _serve(a, prompts)
        a.request_preempt(8)
        deadline = time.time() + 30
        while a.stats()["preempted_pages"] == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert a.stats()["preempted_pages"] > 0
        assert len(store) > 0
    finally:
        a.close()
    b = ContinuousBatcher(
        CFG, params_b, config=ContinuousConfig(**_FCFG), host_store=store
    )
    try:
        before = len(store)
        out = _serve(b, prompts)  # same chains, different weights
        s_b = b.stats()
    finally:
        b.close()
    assert s_b["offload_restored_pages"] == 0  # scope mismatch = miss
    assert len(out) == 2 and all(isinstance(t, str) for t in out)
    assert len(store) == before  # B re-prefilled; nothing was consumed


# ---------------------------------------------------------------------------
# Affinity routing: the panel lands on its donor's replica
# ---------------------------------------------------------------------------


def test_affinity_burst_lands_on_donor_replica(params):
    fleet = _fleet(params)
    try:
        texts = _serve(fleet, [_HEADER + f"q{i}" for i in range(4)])
        s = fleet.stats()
        # Every mate routed to ONE replica (1 load/first + 3 prefix).
        assert s["routed_prefix"] == 3
        per_req = [sum(r.values()) for r in s["routed"]]
        assert sorted(per_req) == [0, 4]
        donor = per_req.index(4)
        per = s["per_replica"]
        assert per[donor]["completed_requests"] == 4
        assert per[1 - donor]["completed_requests"] == 0
        # The shared header prefilled ONCE fleet-wide: the other
        # replica ran no prefill chunks at all, and the donor's
        # registry served the mates' pages.
        assert per[1 - donor]["prefill_chunks"] == 0
        assert per[donor]["prefix_pages_shared"] >= 9  # 3 pages x 3 mates
        assert s["prefix_hit_rate"] == pytest.approx(0.75)
        # Unique traffic still spreads across replicas by modeled load.
        _serve(fleet, [f"{i} unique prompt with its own padding {i}" for i in range(4)])
        s2 = fleet.stats()
        assert all(
            p["completed_requests"] > 0 for p in s2["per_replica"]
        )
        assert len(texts) == 4
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# Preempt -> demote -> re-admit: byte-identical, nothing lost
# ---------------------------------------------------------------------------


def test_preempt_demote_readmit_round_trip_byte_identical(params):
    """The overload contract: a router-requested preemption demotes
    the panel's resident chains to the shared tier; re-sending the
    same panel then RESTORES them — and the round-tripped texts are
    byte-identical to the originals (the demote/restore path is
    bit-exact, PR 4, now router-triggered)."""
    fleet = _fleet(params)
    try:
        prompts = [_HEADER + f"q{i}" for i in range(3)]
        want = _serve(fleet, prompts)
        # Queue-full moment: the hook preempts (tier has headroom).
        assert fleet.preempt_for_admission() is True
        deadline = time.time() + 30
        while (
            fleet.stats()["preempted_pages"] == 0
            and time.time() < deadline
        ):
            time.sleep(0.02)
        s1 = fleet.stats()
        assert s1["preempted_pages"] > 0
        assert sum(s1["preempt_requests"]) == 1
        assert s1["shared_store_pages"] > 0
        # Re-admit the same panel: the chains come back from the tier.
        got = _serve(fleet, prompts)
        s2 = fleet.stats()
    finally:
        fleet.close()
    assert got == want
    assert s2["offload_restored_pages"] > 0


def test_preempt_hook_sheds_only_when_tier_exhausted(params):
    """The hook's False conditions: no tier at all, a tier too full to
    absorb one more page without evicting preserved work, or traffic
    that registers NOTHING shareable (an unbounded queue with nothing
    to ever preempt must keep its classic 429 backpressure)."""
    no_tier = _fleet(params, host_cache_bytes=0)
    try:
        assert no_tier.store is None
        assert no_tier.preempt_for_admission() is False
    finally:
        no_tier.close()
    fleet = _fleet(params)
    try:
        # Nothing registered yet: nothing to preserve => shed.
        assert fleet.preempt_for_admission() is False
        _serve(fleet, [_HEADER + "q0"])
        # Resident chains + tier headroom: preempt instead of shed.
        assert fleet.preempt_for_admission() is True
        # Shrink the headroom below one page: exhaustion => shed.
        fleet.store.budget_bytes = 1
        assert fleet.preempt_for_admission() is False
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# Rebalance: move the chain through the shared store
# ---------------------------------------------------------------------------


def test_rebalance_chain_next_hit_restores_remotely(params):
    fleet = _fleet(params)
    try:
        prompts = [_HEADER + f"q{i}" for i in range(2)]
        want = _serve(fleet, prompts)
        s0 = fleet.stats()
        donor = max(
            range(2),
            key=lambda i: s0["per_replica"][i]["completed_requests"],
        )
        owner = fleet.rebalance_chain(_HEADER + "q0")
        assert owner == donor
        s1 = fleet.stats()
        assert s1["exported_pages"] >= 3  # the header's full pages
        assert s1["shared_store_pages"] >= 3
        # The chain is now restorable ANYWHERE: the other replica's
        # admission host-hits and restores it remotely.
        other = 1 - donor
        got = fleet.submit_to(
            other, _HEADER + "q0", max_new_tokens=4
        ).result(timeout=300)
        s2 = fleet.stats()
        assert got.text == want[0]
        assert s2["per_replica"][other]["offload_restored_pages"] >= 3
    finally:
        fleet.close()


def test_router_rebalances_away_from_congested_owner(params):
    """Auto-rebalance: the affinity owner's batcher queue is deeper
    than the configured bound, so the router exports the chain and
    re-homes the mate to the idle replica — correctness unchanged."""
    fleet = _fleet(params, fleet_kw={"rebalance_waiting": 0})
    try:
        donor_text = _serve(fleet, [_HEADER + "q0"])[0]
        s0 = fleet.stats()
        donor = max(
            range(2),
            key=lambda i: s0["per_replica"][i]["completed_requests"],
        )
        # Congest the donor: more work than its slots, so its batcher
        # queue is non-empty when the mate routes.
        blockers = [
            fleet.submit_to(
                donor, f"{i} blocker with plenty of padding text {i}",
                max_new_tokens=4,
            )
            for i in range(5)
        ]
        fut = fleet.submit(_HEADER + "q0", max_new_tokens=4)
        out = fut.result(timeout=300)
        for b in blockers:
            b.result(timeout=300)
        s = fleet.stats()
    finally:
        fleet.close()
    rebalanced = sum(r["rebalance"] for r in s["routed"])
    assert rebalanced == 1
    assert s["routed"][1 - donor]["rebalance"] == 1
    assert out.text == donor_text  # routing never changes output


# ---------------------------------------------------------------------------
# /readyz aggregation + router health
# ---------------------------------------------------------------------------


def test_wedged_replica_flips_readyz_by_index_and_router_skips(params):
    from llm_consensus_tpu.server.client import (
        GatewayClient,
        GatewayHTTPError,
    )
    from llm_consensus_tpu.server.gateway import (
        Gateway,
        GatewayConfig,
        GatewayThread,
    )
    from llm_consensus_tpu.server.metrics import MetricsRegistry

    fleet = _fleet(params, fleet_kw={"ready_stall_s": 1.0})
    handle = GatewayThread(
        Gateway(
            FleetBackend(fleet),
            config=GatewayConfig(port=0, ready_stall_s=1.0),
            registry=MetricsRegistry(),
        )
    ).start()
    client = GatewayClient("127.0.0.1", handle.port)

    def poll(want_ready, deadline_s=20.0):
        deadline = time.time() + deadline_s
        last = None
        while time.time() < deadline:
            try:
                doc = client.readyz()
                if doc["ready"] is want_ready:
                    return doc
                last = doc
            except GatewayHTTPError as e:
                if not want_ready and e.status == 503:
                    return json.loads(e.body)
                last = e.body
            time.sleep(0.1)
        raise AssertionError(f"readyz never reached {want_ready}: {last}")

    try:
        doc = poll(True)
        hb = doc["backend"]
        assert len(hb["replicas"]) == 2  # per-replica heartbeats ride
        assert hb["alive"] is True
        # Wedge replica 1's loop (instance attribute shadows the bound
        # method — the same trick the PR-5 readyz test uses).
        fleet.batchers[1]._admit = lambda: time.sleep(3.0)
        try:
            doc = poll(False)
            assert doc["wedged_replicas"] == [1]
            # The router stops routing to the wedged replica...
            assert fleet.router.healthy() == [0]
            # ...and live traffic still completes on the healthy one.
            before = fleet.batchers[0].stats()["completed_requests"]
            out = fleet.submit(
                "traffic while replica 1 is wedged", max_new_tokens=4
            ).result(timeout=300)
            assert isinstance(out.text, str)
            assert (
                fleet.batchers[0].stats()["completed_requests"]
                == before + 1
            )
        finally:
            del fleet.batchers[1]._admit
        poll(True)  # recovery
    finally:
        handle.drain()
        fleet.close()


# ---------------------------------------------------------------------------
# Metrics <-> stats lockstep
# ---------------------------------------------------------------------------


def test_replica_metrics_stats_lockstep(params):
    def routed_snapshot():
        out = {}
        for key, v in REGISTRY.snapshot().items():
            m = re.match(
                r'gateway_replica_routed_total\{reason="(\w+)",'
                r'replica="(\d+)"\}',
                key,
            )
            if m:
                out[(int(m.group(2)), m.group(1))] = v
        return out

    r0 = routed_snapshot()
    pre0 = {
        i: REGISTRY.get("gateway_replica_preemptions_total")
        .labels(replica=str(i))
        .value
        for i in (0, 1)
    }
    fleet = _fleet(params)
    try:
        _serve(fleet, [_HEADER + f"q{i}" for i in range(3)])
        assert fleet.preempt_for_admission() is True
        s = fleet.stats()
        r1 = routed_snapshot()
        # Routed counters move exactly with the stats() mirror.
        for i, reasons in enumerate(s["routed"]):
            for reason, n in reasons.items():
                if n:
                    assert r1.get((i, reason), 0) - r0.get((i, reason), 0) == n
        # Preemption counter mirrors preempt_requests per replica.
        for i in (0, 1):
            delta = (
                REGISTRY.get("gateway_replica_preemptions_total")
                .labels(replica=str(i))
                .value
                - pre0[i]
            )
            assert delta == s["preempt_requests"][i]
        # Gauges refreshed by the stats pull match the per-replica
        # stats they were computed from.
        for i, per in enumerate(s["per_replica"]):
            programs = sum(
                per[f"device_programs_{k}"]
                for k in ("fused", "decode", "prefill", "spec", "draft")
            )
            g = REGISTRY.get("gateway_replica_programs").labels(
                replica=str(i)
            )
            assert g.value == programs
            hr = REGISTRY.get("gateway_replica_prefix_hit_rate").labels(
                replica=str(i)
            )
            assert hr.value == pytest.approx(
                per["prefix_hits"] / max(1, per["prefix_lookups"])
            )
        assert REGISTRY.get(
            "gateway_replica_shared_store_bytes"
        ).value == s["shared_store_bytes"]
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# The bench A/B leg (subprocess, CPU smoke sizes)
# ---------------------------------------------------------------------------


def test_bench_serve_replicas_cpu_ab_leg(tmp_path: Path):
    """Acceptance: K=2 affinity hit rate strictly above the
    random-routing control, per-pair byte-identical text, and the
    overload storm resolves via preemption — zero 429s, zero lost
    requests, >= 1 preempt and >= 1 restored page."""
    out = tmp_path / "replicas_ab.json"
    r = subprocess.run(
        [
            sys.executable, "bench.py", "--tiny", "--cpu",
            "--serve-replicas", "2", "--serve-requests", "8",
            "--serve-slots", "2", "--new-tokens", "6",
            "--prompt-len", "64", "--serve-chunk", "1",
            "--serve-prefill-chunk", "64", "--out", str(out),
        ],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads(out.read_text())
    assert payload["status"] == "ok"
    m = payload["metric"]
    hits = re.search(
        r"hit-rate affinity ([\d.]+) vs random ([\d.]+)", m
    )
    assert float(hits.group(1)) > float(hits.group(2))
    assert "429s 0," in m and "lost 0," in m
    assert int(re.search(r"preempts (\d+)", m).group(1)) >= 1
    assert int(re.search(r"restored (\d+)", m).group(1)) >= 1
    assert "text unchanged=True" in m
