"""Speculative decoding inside the continuous batcher (PR 9).

Contract layers:

- ACCEPT RULE: `engine.accept.verify_row` / `verify_tokens` pin to the
  standalone `engine.speculative.speculative_generate` decisions — the
  parity oracle (same greedy-match / leviathan one-hot math, one shared
  `leviathan_accept`).
- KERNEL/REFERENCE: the ragged attention verify lane ([B, NQ, H, D]
  decode rows) matches the XLA reference on mixed shapes.
- BATCHER: with a draft model + `spec_k > 0`, each round dispatches ONE
  draft/verify/accept device program emitting a ragged budget of
  verified tokens per slot. Greedy text is BYTE-IDENTICAL to spec-off
  for any draft — across pipeline depths, chunk widths, spec_k,
  sliding windows, rollbacks landing on page boundaries, panel members
  diverging from a shared draft stream, eviction + host-tier restores
  in flight, and a zero-acceptance draft (which degrades to plain
  decode progress, never a livelock).
"""

import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.accept import (
    leviathan_accept,
    verify_row,
    verify_tokens,
)
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)

CFG = get_config("test-tiny")
DCFG = get_config("test-tiny-draft")

_CCFG = dict(
    max_slots=4,
    page_size=16,
    n_pages=96,
    pages_per_seq=10,
    max_new_tokens=10,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
)

_HEADER = "Panel shared header for every persona, forty ch: "


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


@pytest.fixture(scope="module")
def dparams():
    # Random draft weights: proposes garbage, accepts ~nothing — the
    # adversarial draft. Correctness must not depend on acceptance.
    return init_params(DCFG, jax.random.PRNGKey(1), dtype=jnp.float32)


def _serve(batcher, prompts, **kw):
    futs = [batcher.submit(p, **kw) for p in prompts]
    return [f.result(timeout=180) for f in futs]


def _quiesce(batcher, timeout=20.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        s = batcher.stats()
        if (
            s["active_slots"] == 0
            and s["prefilling_slots"] == 0
            and s["dispatch_inflight"] == 0
            and s["waiting"] == 0
        ):
            return s
        time.sleep(0.01)
    return batcher.stats()


def _burst(params, draft, spec_k, depth=2, chunk=16, cfg=CFG, cfgkw=None,
           prompts=None, **submit_kw):
    ccfg = dict(_CCFG, prefill_chunk=chunk)
    ccfg.update(cfgkw or {})
    b = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(
            **ccfg, pipeline_depth=depth, spec_k=spec_k
        ),
        draft=draft,
    )
    prompts = prompts or [
        _HEADER + "alpha tail one",
        _HEADER + "beta tail two",
        "unrelated prompt entirely",
        _HEADER + "gamma tail three",
    ]
    try:
        return [r.text for r in _serve(b, prompts, **submit_kw)], b.stats()
    finally:
        b.close()


# ---------------------------------------------------------------------------
# Accept rule: pinned to the standalone oracle's decisions
# ---------------------------------------------------------------------------


def _oracle_row(logits, drafts, temperature, keys):
    """The standalone ``speculative_generate`` verify math for one row,
    re-composed from its building blocks (decode_chunk's argmax chain +
    the one-hot leviathan call) — the decisions `verify_row` must pin
    to exactly."""
    k = drafts.shape[0]
    v = logits.shape[-1]
    targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    greedy = temperature <= 0.0
    if greedy:
        match = drafts == targets[:k]
        fix_of = targets
    else:
        p = jax.nn.softmax(logits / jnp.maximum(temperature, 1e-6), axis=-1)
        q = jnp.concatenate(
            [jax.nn.one_hot(drafts, v, dtype=p.dtype), jnp.zeros((1, v))]
        )
        d_pad = jnp.pad(drafts, (0, 1))
        coin, corr = jax.vmap(leviathan_accept)(p, q, d_pad, keys)
        match = coin[:k]
        fix_of = corr
    acc = jnp.cumprod(match.astype(jnp.int32))
    n_acc = int(jnp.sum(acc))
    emit = list(np.asarray(drafts[:n_acc])) + [int(fix_of[n_acc])]
    return emit, n_acc + 1


@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_verify_row_pins_to_standalone_accept(temperature):
    rng = np.random.default_rng(7)
    k, v = 4, 48
    for trial in range(8):
        logits = jnp.asarray(rng.standard_normal((k + 1, v)), jnp.float32)
        # Mix of agreeing and disagreeing drafts.
        greedy_t = np.asarray(jnp.argmax(logits, axis=-1))
        drafts = np.where(
            rng.random(k) < 0.5, greedy_t[:k], rng.integers(0, v, k)
        ).astype(np.int32)
        keys = jax.vmap(
            lambda j: jax.random.fold_in(jax.random.PRNGKey(trial), j)
        )(jnp.arange(k + 1))
        emit, cnt = verify_row(
            logits, jnp.asarray(drafts), jnp.float32(temperature), keys
        )
        want_emit, want_cnt = _oracle_row(
            logits, jnp.asarray(drafts), temperature, keys
        )
        assert int(cnt) == want_cnt
        assert list(np.asarray(emit[:cnt])) == want_emit


def test_verify_tokens_greedy_is_argmax_chain_and_filter_invariant():
    """Greedy rows: the emitted chain equals the per-position argmax,
    and top-k/top-p filters (which keep the argmax) cannot change it."""
    rng = np.random.default_rng(8)
    b, k, v = 3, 3, 32
    logits = jnp.asarray(rng.standard_normal((b, k + 1, v)), jnp.float32)
    greedy_t = np.asarray(jnp.argmax(logits, axis=-1))
    drafts = jnp.asarray(greedy_t[:, :k]).at[1, 1].add(1)  # row 1 rejects @1
    temps = jnp.zeros((b,), jnp.float32)
    keys = jnp.broadcast_to(
        jax.random.PRNGKey(0), (b, k + 1, 2)
    )
    for fa, tk in ((False, 0), (True, 5)):
        for ag in (False, True):
            # all_greedy=True is the batcher's static fast path (no
            # leviathan machinery) — bit-identical to the general path
            # on greedy rows.
            emit, cnt = verify_tokens(
                logits, drafts, temps,
                jnp.full((b,), tk, jnp.int32),
                jnp.full((b,), 0.9, jnp.float32),
                keys, filters_active=fa, all_greedy=ag,
            )
            assert list(np.asarray(cnt)) == [k + 1, 2, k + 1]
            for i in range(b):
                n = int(cnt[i])
                assert list(np.asarray(emit[i, :n])) == list(greedy_t[i, :n])


# ---------------------------------------------------------------------------
# Ragged verify lane: kernel vs XLA reference
# ---------------------------------------------------------------------------


def test_ragged_verify_rows_match_reference():
    """[B, NQ, H, D] verify rows — with a chunk lane riding along and a
    sliding window — match the reference's chunk_decode_attention rule
    per row (the kernel's nq > 1 decode lane, PR 9)."""
    from llm_consensus_tpu.ops.attention import (
        ragged_paged_attention_reference,
    )
    from llm_consensus_tpu.ops.pallas.attention import ragged_paged_attention

    rng = np.random.default_rng(11)
    pg, hkv, d, g, b, p_per, nq, cq = 8, 2, 32, 3, 4, 6, 3, 8
    h = hkv * g
    kp = jnp.asarray(rng.standard_normal((40, pg, hkv, d)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((40, pg, hkv, d)), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((b, nq, h, d)), jnp.bfloat16)
    qc = jnp.asarray(rng.standard_normal((cq, h, d)), jnp.bfloat16)
    perm = rng.permutation(np.arange(1, 40))
    tbl = jnp.asarray(perm[: b * p_per].reshape(b, p_per), jnp.int32)
    ctbl = jnp.asarray(perm[b * p_per : b * p_per + p_per], jnp.int32)
    vl = jnp.asarray([13, 5, 40, 23], jnp.int32)  # >= nq, mid-block
    for window in (0, 9):
        got_d, got_c = ragged_paged_attention(
            q, kp, vp, tbl, vl, q_chunk=qc, chunk_table=ctbl,
            chunk_start=jnp.int32(11), window=window, interpret=True,
        )
        ref_d, ref_c = ragged_paged_attention_reference(
            q, kp, vp, tbl, vl, q_chunk=qc, chunk_table=ctbl,
            chunk_start=jnp.int32(11), window=window,
        )
        np.testing.assert_allclose(
            np.asarray(got_d, np.float32), np.asarray(ref_d, np.float32),
            rtol=2e-2, atol=2e-2,
        )
        np.testing.assert_allclose(
            np.asarray(got_c, np.float32), np.asarray(ref_c, np.float32),
            rtol=2e-2, atol=2e-2,
        )


def test_ragged_verify_rows_grouped_match_reference():
    """Verify rows through the GROUP phase: every member query stacks
    against one read of the shared run; output equals the ungrouped
    reference."""
    from llm_consensus_tpu.ops.attention import (
        ragged_paged_attention_reference,
    )
    from llm_consensus_tpu.ops.pallas.attention import ragged_paged_attention

    rng = np.random.default_rng(12)
    pg, hkv, d, g, b, p_per, nq = 8, 2, 32, 3, 4, 6, 3
    h = hkv * g
    kp = jnp.asarray(rng.standard_normal((40, pg, hkv, d)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((40, pg, hkv, d)), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((b, nq, h, d)), jnp.bfloat16)
    tbl = np.asarray(
        rng.permutation(np.arange(1, 40))[: b * p_per].reshape(b, p_per),
        np.int32,
    )
    tbl[2, 0] = tbl[0, 0]
    tbl[3, 0] = tbl[0, 0]
    tbl = jnp.asarray(tbl)
    vl = jnp.asarray([13, 9, 40, 23], jnp.int32)
    groups = (
        jnp.asarray([0, -1, 0, 0], jnp.int32),
        jnp.asarray([0], jnp.int32),
        jnp.asarray([pg], jnp.int32),
        jnp.asarray([pg, 0, pg, pg], jnp.int32),
    )
    for window in (0, 9):
        got = ragged_paged_attention(
            q, kp, vp, tbl, vl, groups=groups, window=window, interpret=True
        )
        ref = ragged_paged_attention_reference(
            q, kp, vp, tbl, vl, window=window
        )
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref, np.float32),
            rtol=2e-2, atol=2e-2,
        )


# ---------------------------------------------------------------------------
# Batcher: spec-on vs spec-off byte parity
# ---------------------------------------------------------------------------


def test_spec_text_parity_grid(params, dparams):
    """THE acceptance contract: greedy text byte-identical spec-on vs
    spec-off across pipeline depth {1,2} x chunk {16,32} x spec_k
    {2,4}, with the adversarial (random-weights) draft — the draft
    only ever affects speed."""
    want, _ = _burst(params, None, 0, depth=1, chunk=16)
    for depth in (1, 2):
        for chunk in (16, 32):
            for spec_k in (2, 4):
                got, st = _burst(
                    params, (DCFG, dparams), spec_k, depth=depth, chunk=chunk
                )
                assert got == want, (depth, chunk, spec_k)
                assert st["device_programs_spec"] >= 1


def test_spec_parity_high_acceptance_self_draft(params):
    """Self-draft (target as its own draft): acceptance near 1, rounds
    emit multiple tokens per program, text still byte-identical."""
    want, _ = _burst(params, None, 0, depth=1)
    got, st = _burst(params, (CFG, params), 4)
    assert got == want
    # Multi-token rounds actually happened: fewer spec programs than
    # generated tokens, and a healthy acceptance mean.
    toks = st["generated_tokens"]
    assert st["device_programs_spec"] < toks
    acc = st["spec_acceptance_sum"] / max(1, st["spec_acceptance_count"])
    assert acc > 0.5
    assert st["spec_accepted_tokens"] > 0


def test_spec_sliding_window_config_parity(params, dparams):
    """The windowed config rides the same verify lane (per-query window
    edges inside the ragged mask) — parity must hold there too."""
    wcfg = CFG.with_(sliding_window=24)
    want, _ = _burst(params, None, 0, depth=1, cfg=wcfg)
    for draft in ((DCFG, dparams), (CFG, params)):
        got, _ = _burst(params, draft, 3, cfg=wcfg)
        assert got == want


def test_spec_rollback_on_page_boundary(params, dparams):
    """Zero-acceptance rollback landing exactly on page boundaries:
    page_size 16 prompts sized to put early verify rounds astride a
    boundary — the rejected tail's K/V crosses into the next private
    page and is rewound by count bookkeeping alone. Parity is the
    proof the garbage never leaks into attention."""
    # prompt of exactly 15/16/17 tokens: first verify rounds write
    # across position 16 (the page-1 edge) in every alignment.
    prompts = ["x" * 15, "y" * 16, "z" * 17, _HEADER + "boundary"]
    want, _ = _burst(params, None, 0, depth=1, prompts=prompts)
    for spec_k in (2, 4):
        got, st = _burst(
            params, (DCFG, dparams), spec_k, prompts=prompts
        )
        assert got == want, spec_k
        # The adversarial draft really was rejected ~always.
        acc = st["spec_acceptance_sum"] / max(1, st["spec_acceptance_count"])
        assert acc < 0.5


def test_spec_zero_acceptance_never_livelocks(params, dparams):
    """A draft that accepts nothing degrades to >= plain-decode
    progress: every round still emits the correction token, so the
    burst completes (within the future timeout) with byte-identical
    text and every request retired."""
    want, _ = _burst(params, None, 0, depth=1)
    got, st = _burst(params, (DCFG, dparams), 4)
    assert got == want
    assert st["active_slots"] == 0 and st["waiting"] == 0
    assert st["completed_requests"] >= 4
    # Progress floor: one spec program never emits fewer tokens than a
    # plain decode step would have.
    assert st["generated_tokens"] >= st["device_programs_spec"]


def test_spec_shared_stream_and_mid_group_divergence(params):
    """The panel amortization: members over one header share the
    donor's draft stream while their committed texts agree, and a
    member that diverges (different tail -> different greedy output)
    drops back to its own stream while the group keeps decoding.
    Greedy parity holds throughout; the unique-prompt control run
    shares nothing."""
    # Three members share the whole prompt (identical committed text —
    # greedy mates agree forever, staggered activations catch up via
    # the donor's committed-suffix fill); the fourth carries its own
    # tail, so its greedy output diverges from the donor's committed
    # text and it drafts for itself while the group keeps decoding.
    panel = [_HEADER + "same question"] * 3 + [_HEADER + "diverging tail"]
    want, _ = _burst(params, None, 0, depth=1, prompts=panel)
    got, st = _burst(params, (CFG, params), 4, prompts=panel)
    assert got == want
    assert st["spec_shared_draft_rows"] > 0
    # Distinct from byte 0 — with page_size 16, prompts differing only
    # mid-string would still share their first page (and legitimately
    # group); the control must not.
    unique = [f"{i} <- unique prompt with its own header" for i in range(4)]
    _, st_u = _burst(params, (CFG, params), 4, prompts=unique)
    assert st_u["spec_shared_draft_rows"] == 0
    # Sharing reduced draft tokens per generated token vs the
    # per-sequence control (the ISSUE's amortization gate, CPU-sized).
    rate_panel = st["spec_draft_tokens"] / max(1, st["generated_tokens"])
    rate_unique = st_u["spec_draft_tokens"] / max(1, st_u["generated_tokens"])
    assert rate_panel < rate_unique


def test_spec_eviction_and_host_restore_in_flight(params, dparams):
    """Host-tier demote/restore with speculation engaged: the draft
    pool's planes travel with the target's (4-plane store entries), the
    restored prefix keeps draft context, and text parity holds across
    the eviction round trip."""
    cfgkw = dict(
        max_slots=2,
        page_size=16,
        n_pages=17,  # 16 usable vs a 2x8-page unshared working set
        pages_per_seq=10,
        max_new_tokens=6,
        seq_buckets=(16, 32, 64),
        prefill_chunk=16,
        share_prefix=True,
        host_cache_bytes=8 << 20,
    )
    rounds = [
        [_HEADER + f"p{i} proposes" for i in range(2)],
        [
            f"{i} unique filler storm with plenty of padding text {i}"
            for i in range(4)
        ],
        [_HEADER + f"r{i} re-votes" for i in range(2)],
    ]

    def run(draft, spec_k):
        b = ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(**cfgkw, spec_k=spec_k),
            draft=draft,
        )
        try:
            texts = []
            for r in rounds:
                texts.append([x.text for x in _serve(b, r)])
            return texts, b.stats()
        finally:
            b.close()

    want, st_off = run(None, 0)
    got, st_on = run((DCFG, dparams), 3)
    assert got == want
    assert st_on["offload_restored_pages"] >= 1
    assert st_on["offload_restored_pages"] == st_off["offload_restored_pages"]


def test_spec_does_not_engage_with_steps_per_sync(params, dparams):
    """steps_per_sync > 1 means the decode program folds k steps; the
    verify round doesn't compose with that scan — speculation stays
    off (plain programs run, parity vs the no-draft batcher holds)."""
    want, _ = _burst(params, None, 0, cfgkw=dict(steps_per_sync=2))
    got, st = _burst(
        params, (DCFG, dparams), 3, cfgkw=dict(steps_per_sync=2)
    )
    assert got == want
    assert st["device_programs_spec"] == 0
    assert st["spec_draft_tokens"] == 0


def test_spec_flip_on_one_batcher(params):
    """config.spec_decode is the live A/B lever: one batcher serves a
    spec-on burst then a spec-off burst, both byte-identical to the
    no-draft baseline (the flip drains the pipeline, so plain and spec
    programs never share a window)."""
    prompts = [_HEADER + "flip one", _HEADER + "flip two"]
    want, _ = _burst(params, None, 0, depth=1, prompts=prompts)
    b = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**_CCFG, spec_k=3),
        draft=(CFG, params),
    )
    try:
        on = [r.text for r in _serve(b, prompts)]
        _quiesce(b)
        st_on = b.stats()
        b.config.spec_decode = False
        off = [r.text for r in _serve(b, prompts)]
        _quiesce(b)
        st_off = b.stats()
    finally:
        b.close()
    assert on == want and off == want
    assert st_on["device_programs_spec"] >= 1
    assert st_off["device_programs_spec"] == st_on["device_programs_spec"]
    assert st_off["device_programs_decode"] > st_on["device_programs_decode"]


def test_spec_flip_mid_decode_replays_draft_mirror(params):
    """spec_decode flipped OFF with rows mid-decode and back ON: the
    plain window advances only the target cache, so each surviving
    row's draft mirror goes stale (``_Slot.draft_lag``). Re-engaging
    must replay the window through the draft (chunk-wide + width-1
    catch-up programs) and re-install the row's draft length BEFORE
    the next spec dispatch — a stale mirror would write the row's next
    draft K/V at shifted positions and collapse the self draft's
    acceptance for the rest of the row's life (text parity would still
    hold; what dies is the speedup the flip is supposed to resume)."""
    cfgkw = dict(_CCFG, max_new_tokens=32)
    # Unique prompts: no shared-prefix group, every row drafts for
    # itself — acceptance isolates the mirror's health from the
    # shared-stream machinery.
    prompts = [
        "unrelated prompt one entirely",
        "second distinct prompt here",
    ]
    want, _ = _burst(
        params, None, 0, depth=1, cfgkw=dict(max_new_tokens=32),
        prompts=prompts, max_new_tokens=32,
    )
    b = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**cfgkw, spec_k=3),
        draft=(CFG, params),
    )
    b.config.spec_decode = False  # rows enter decode under PLAIN rounds
    state = {"plain": 0, "draft_at_flip": None}
    real_dispatch = b._dispatch

    def flipping_dispatch(chunk_idx=None, spec=False, **kw):
        if not spec:
            state["plain"] += 1
            # Past one full chunk width of lag (20 > prefill_chunk 16):
            # the replay exercises the chunk-wide window AND the
            # width-1 tail.
            if state["plain"] == 20:
                state["draft_at_flip"] = b.stats()["device_programs_draft"]
                b.config.spec_decode = True
        real_dispatch(chunk_idx, spec=spec, **kw)

    b._dispatch = flipping_dispatch
    try:
        texts = [r.text for r in _serve(b, prompts, max_new_tokens=32)]
        st = _quiesce(b)
    finally:
        b.close()
    assert texts == want
    assert state["plain"] >= 20 and st["device_programs_spec"] >= 1
    # The replay ran: catch-up draft programs beyond the admission
    # chunk mirrors.
    assert st["device_programs_draft"] > state["draft_at_flip"]
    # ...and restored the mirror: the self draft proposes the target's
    # own greedy chain again, so post-flip rounds keep accepting.
    acc = st["spec_acceptance_sum"] / max(1, st["spec_acceptance_count"])
    assert acc > 0.9


def test_spec_metrics_prometheus_stats_lockstep(params):
    """The four PR-9 Prometheus families move by the batcher's own
    stats() deltas — one instrumentation site, two surfaces."""
    from llm_consensus_tpu.server.metrics import (
        DEVICE_PROGRAMS,
        SPEC_ACCEPTANCE,
        SPEC_ACCEPTED_TOKENS,
        SPEC_DRAFT_TOKENS,
        SPEC_VERIFIED_TOKENS,
    )

    before = {
        "drafted": SPEC_DRAFT_TOKENS.value,
        "accepted": SPEC_ACCEPTED_TOKENS.value,
        "acc_count": SPEC_ACCEPTANCE.count,
        "acc_sum": SPEC_ACCEPTANCE.sum,
        "spec": DEVICE_PROGRAMS.labels(kind="spec").value,
        "draft": DEVICE_PROGRAMS.labels(kind="draft").value,
    }
    _, st = _burst(params, (CFG, params), 3)
    assert SPEC_DRAFT_TOKENS.value - before["drafted"] == (
        st["spec_draft_tokens"]
    )
    assert SPEC_ACCEPTED_TOKENS.value - before["accepted"] == (
        st["spec_accepted_tokens"]
    )
    assert SPEC_ACCEPTANCE.count - before["acc_count"] == (
        st["spec_acceptance_count"]
    )
    assert SPEC_ACCEPTANCE.sum - before["acc_sum"] == pytest.approx(
        st["spec_acceptance_sum"]
    )
    assert DEVICE_PROGRAMS.labels(kind="spec").value - before["spec"] == (
        st["device_programs_spec"]
    )
    assert DEVICE_PROGRAMS.labels(kind="draft").value - before["draft"] == (
        st["device_programs_draft"]
    )
    # The gauge is last-write (no delta): both surfaces hold the final
    # spec program's emitted-token count.
    assert SPEC_VERIFIED_TOKENS.value == st["spec_verified_tokens_last"]


def test_spec_stop_sequences_parity(params, dparams):
    """Multi-token string stops landing inside a multi-token emission:
    the fetch scans emitted tokens one at a time, so stop-trim and
    retirement stay byte-identical to spec-off."""
    prompts = [_HEADER + "one", _HEADER + "two", _HEADER + "three"]
    kw = dict(prompts=prompts, temperature=0.9, seed=3, stop=["\x00", "ab"])
    want, _ = _burst(params, None, 0, depth=1, **kw)
    got, _ = _burst(params, (DCFG, dparams), 3, **kw)
    # Sampled rows keep their (seed, index) PRNG addressing, but the
    # accept rule burns keys differently than the plain sampler — only
    # GREEDY rows promise byte parity. temperature=0.9 here exercises
    # the stop machinery under spec; parity is asserted on the greedy
    # variant below.
    assert [len(t) >= 0 for t in got]
    kw_greedy = dict(prompts=prompts, stop=["\x00", "ab"])
    want_g, _ = _burst(params, None, 0, depth=1, **kw_greedy)
    got_g, _ = _burst(params, (DCFG, dparams), 3, **kw_greedy)
    assert got_g == want_g


def test_spec_stream_plan_stale_mirror_skips_fill(params, dparams):
    """The pipeline staleness rule: with a program in flight the host
    mirror lags the device by a data-dependent round, so a donor-
    suffix FILL (off > 0) planned from it would verify at shifted
    positions — the plan must skip it (the mate drafts for itself)
    while delta-0 sharing (donor's fresh proposals, position-free)
    stays planned. With the window empty the catch-up fill plans."""
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(**_CCFG, spec_k=4),
        draft=(DCFG, dparams),
    )
    try:
        prompts = [_HEADER + "stream plan"] * 3
        _serve(b, prompts, max_new_tokens=4)
        # Rebuild a staggered decode state by hand: three slots over
        # one registered header run, mate 1 in lockstep with the
        # donor, mate 2 two tokens behind.
        for i in range(3):
            b._groups.add(i, b._slots[i].pages[:2] if b._slots[i] else [])
        donor_gen = [7, 8, 9, 10]

        class _S:
            # Neutral phase: the live loop thread scans _slots
            # concurrently (pick-prefill, _decoding, spec catch-up)
            # and must skip these stand-ins, not crash on them.
            phase = "held"

            def __init__(self, gen):
                self.generated = list(gen)

        b._slots[0] = _S(donor_gen)
        b._slots[1] = _S(donor_gen)
        b._slots[2] = _S(donor_gen[:2])
        run = (101, 102)
        b._groups._run_of_seq = {0: run, 1: run, 2: run}
        rows_now = [(0, b._slots[0]), (1, b._slots[1]), (2, b._slots[2])]
        b._inflight.clear()
        src, fill, off, streams, shared = b._spec_stream_plan(rows_now)
        assert list(src[:3]) == [0, 0, 0] and int(off[2]) == 2
        assert shared == 2 and streams == 1
        assert list(fill[2, :2]) == donor_gen[2:]
        b._inflight.append(object())  # a program in flight: stale mirror
        src, fill, off, streams, shared = b._spec_stream_plan(rows_now)
        assert list(src[:3]) == [0, 0, 2]  # the lagging fill is skipped
        assert int(off[1]) == 0 and shared == 1 and streams == 2
        b._inflight.clear()
    finally:
        b._slots = [None] * b.config.max_slots  # drop the stand-ins
        b.close()


def test_bench_serve_speculative_cpu_ab_leg():
    """The CPU-run A/B leg (acceptance): spec-on/off byte-identical
    text through one batcher, verified tokens per spec device program
    > 1.0 (the self-draft ceiling), panel draft rate below the
    unique-prompt control, rc 0."""
    r = subprocess.run(
        [
            sys.executable, "bench.py", "--tiny", "--cpu",
            "--serve-speculative", "--serve-requests", "6",
            "--serve-slots", "3", "--new-tokens", "8",
            "--prompt-len", "96", "--serve-prefill-chunk", "64",
            "--k-spec", "3", "--spec-ab-rounds", "1",
        ],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "verified tokens/program" in r.stdout
    assert "text unchanged=True" in r.stdout
