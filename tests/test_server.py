"""Serving gateway tests: admission, deadlines, SSE, drain, metrics.

CPU-only — the gateway fronts :class:`FakeBackend`, so these exercise
the full network path (hand-rolled HTTP/1.1, admission queues, SSE
framing, Prometheus exposition) without a model. The integration test
at the bottom is the acceptance scenario: >= 32 concurrent mixed
generate/consensus requests against a queue bound that forces sheds,
every request reaching exactly one terminal outcome, metrics consistent
with the observed outcomes, and graceful drain completing all admitted
work.
"""

import collections
import json
import threading
import time
from pathlib import Path

import pytest

from llm_consensus_tpu.backends.fake import FakeBackend
from llm_consensus_tpu.server.admission import AdmissionConfig
from llm_consensus_tpu.server.client import GatewayClient, GatewayHTTPError
from llm_consensus_tpu.server.gateway import (
    Gateway,
    GatewayConfig,
    GatewayThread,
)
from llm_consensus_tpu.server.metrics import REGISTRY, MetricsRegistry


def _boot(backend, admission=None, **gw_kw):
    """Gateway on an ephemeral port with an isolated registry."""
    reg = MetricsRegistry()
    gw = Gateway(
        backend,
        config=GatewayConfig(
            port=0, admission=admission or AdmissionConfig(), **gw_kw
        ),
        registry=reg,
    )
    handle = GatewayThread(gw).start()
    return handle, GatewayClient("127.0.0.1", handle.port), reg


# ---------------------------------------------------------------------------
# Metrics registry (unit)
# ---------------------------------------------------------------------------


def test_registry_renders_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("req_total", "requests").inc(3)
    reg.gauge("depth", "queue depth").set(2)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render()
    assert "# TYPE req_total counter" in text
    assert "req_total 3" in text
    assert "depth 2" in text
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf"} 3' in text
    assert "lat_seconds_count 3" in text


def test_registry_labels_and_get_or_create():
    reg = MetricsRegistry()
    c = reg.counter("shed_total")
    c.labels(priority="interactive").inc()
    c.labels(priority="batch").inc(2)
    # Same name returns the same family; a kind clash is an error.
    assert reg.counter("shed_total") is c
    with pytest.raises(ValueError):
        reg.gauge("shed_total")
    text = reg.render()
    assert 'shed_total{priority="batch"} 2' in text
    assert 'shed_total{priority="interactive"} 1' in text


# ---------------------------------------------------------------------------
# Basic routes
# ---------------------------------------------------------------------------


def test_generate_healthz_and_errors():
    handle, client, _ = _boot(FakeBackend())
    try:
        assert client.healthz()["status"] == "ok"
        r = client.generate("What is 2+2?")
        assert r["text"] == "Echo: What is 2+2?"
        assert r["num_tokens"] > 0
        # Missing prompt, bad JSON, bad route, bad method.
        with pytest.raises(GatewayHTTPError) as e:
            client.generate("")
        assert e.value.status == 400
        with pytest.raises(GatewayHTTPError) as e:
            client._json("POST", "/v1/nope", {})
        assert e.value.status == 404
        with pytest.raises(GatewayHTTPError) as e:
            client._json("GET", "/v1/generate")
        assert e.value.status == 405
    finally:
        handle.drain()


def test_consensus_route_runs_full_protocol():
    handle, client, reg = _boot(FakeBackend())
    try:
        r = client.consensus("What is the answer?", seed=0)
        assert r["endorsed"] is True
        assert r["rounds"] == 1
        assert set(r["feedback"].values()) == {"Good"}
        assert r["author"] in r["feedback"]
    finally:
        handle.drain()
    # The coordinator's instrumentation feeds the PROCESS-wide registry.
    assert REGISTRY.get("consensus_questions_total").value >= 1


# ---------------------------------------------------------------------------
# SSE streaming
# ---------------------------------------------------------------------------


def test_sse_stream_matches_nonstreaming_output():
    handle, client, _ = _boot(FakeBackend())
    try:
        plain = client.generate("stream me, please")
        events = list(client.stream_generate("stream me, please"))
        # Chunks then exactly one terminal summary event.
        assert events[-1]["done"] is True
        assert events[-1]["num_tokens"] == plain["num_tokens"]
        text = "".join(e.get("text", "") for e in events[:-1])
        assert text == plain["text"]
        assert len(events) > 2  # genuinely chunked, not one blob
    finally:
        handle.drain()


def test_stream_shed_is_plain_http_429():
    backend = FakeBackend(latency=1.0)
    handle, client, reg = _boot(
        backend, admission=AdmissionConfig(max_queue=1, max_inflight=1)
    )
    try:
        # Feed pads until one is genuinely QUEUED behind the full
        # in-flight window. A fixed burst + sleep is loop-scheduling
        # dependent (the dispatcher's first pop races the later
        # submits, so a burst can shed every pad and leave the queue
        # EMPTY right when the stream request lands); polling the depth
        # gauge pins the state the test is actually about.
        depth = reg.get("gateway_queue_depth").labels(priority="interactive")
        threads = []
        deadline = time.time() + 10
        while depth.value < 1 and time.time() < deadline:
            t = threading.Thread(target=lambda: _swallow(client, "pad"))
            t.start()
            threads.append(t)
            time.sleep(0.02)
        assert depth.value >= 1, "never filled the admission queue"
        with pytest.raises(GatewayHTTPError) as e:
            list(client.stream_generate("late"))
        assert e.value.status == 429
        for t in threads:
            t.join()
    finally:
        handle.drain()


def _swallow(client, prompt):
    try:
        client.generate(prompt)
    except GatewayHTTPError:
        pass


# ---------------------------------------------------------------------------
# Admission: shed, Retry-After, deadlines
# ---------------------------------------------------------------------------


def test_overload_sheds_with_retry_after():
    handle, client, reg = _boot(
        FakeBackend(latency=0.15),
        admission=AdmissionConfig(max_queue=2, max_inflight=1),
    )
    outcomes = collections.Counter()
    retry_afters = []

    def worker(i):
        try:
            client.generate(f"q{i}")
            outcomes["ok"] += 1
        except GatewayHTTPError as e:
            outcomes[e.status] += 1
            if e.status == 429:
                retry_afters.append(e.retry_after)

    try:
        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(12)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        handle.drain()
    assert outcomes["ok"] >= 1
    assert outcomes[429] >= 1
    assert outcomes["ok"] + outcomes[429] == 12
    # Shed responses carry a positive Retry-After hint.
    assert all(ra is not None and ra > 0 for ra in retry_afters)
    snap = reg.snapshot()
    assert snap['gateway_shed_total{priority="interactive"}'] == outcomes[429]
    assert (
        snap['gateway_admitted_total{priority="interactive"}']
        == outcomes["ok"]
    )


def test_deadline_expires_queued_and_inflight():
    handle, client, reg = _boot(
        FakeBackend(latency=0.4),
        admission=AdmissionConfig(max_queue=8, max_inflight=1),
    )
    try:
        # In-flight expiry: the only request, deadline < backend latency.
        with pytest.raises(GatewayHTTPError) as e:
            client.generate("slow", deadline_s=0.05)
        assert e.value.status == 504
        # Queued expiry: a long request occupies the single in-flight
        # slot; the second request's deadline passes while still queued.
        t = threading.Thread(target=lambda: _swallow(client, "occupier"))
        t.start()
        time.sleep(0.05)
        with pytest.raises(GatewayHTTPError) as e:
            client.generate("queued", deadline_s=0.05)
        assert e.value.status == 504
        t.join()
    finally:
        handle.drain()
    snap = reg.snapshot()
    assert snap['gateway_deadline_expired_total{priority="interactive"}'] == 2


# ---------------------------------------------------------------------------
# Graceful drain
# ---------------------------------------------------------------------------


def test_graceful_drain_completes_admitted_work():
    handle, client, reg = _boot(
        FakeBackend(latency=0.2),
        admission=AdmissionConfig(max_queue=16, max_inflight=2),
    )
    results = []

    def worker(i):
        try:
            results.append(("ok", client.generate(f"drain{i}")["text"]))
        except Exception as e:  # noqa: BLE001 - recorded for assertion
            results.append(("err", repr(e)))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    # Poll the counter instead of a fixed sleep: drain() must not begin
    # before all six are ADMITTED, or a slow-to-schedule client thread
    # gets DrainingError on the 1-core box.
    admitted = reg.get("gateway_admitted_total").labels(priority="interactive")
    deadline = time.time() + 10
    while admitted.value < 6 and time.time() < deadline:
        time.sleep(0.01)
    assert admitted.value == 6, "threads never all admitted"
    handle.drain()  # blocks until every admitted request completed
    for t in threads:
        t.join()
    # Every admitted request completed with its real result.
    assert [s for s, _ in results] == ["ok"] * 6
    snap = reg.snapshot()
    assert (
        snap['gateway_admitted_total{priority="interactive"}']
        == snap['gateway_completed_total{priority="interactive"}']
        == 6
    )
    # And the socket is gone.
    with pytest.raises(OSError):
        client.healthz()


# ---------------------------------------------------------------------------
# /metrics exposition over HTTP
# ---------------------------------------------------------------------------


def test_metrics_endpoint_content():
    handle, client, _ = _boot(FakeBackend())
    try:
        client.generate("observable")
        text = client.metrics()
    finally:
        handle.drain()
    assert "# TYPE gateway_requests_total counter" in text
    assert (
        'gateway_requests_total{route="/v1/generate",status="200"} 1' in text
    )
    assert "# TYPE gateway_ttft_seconds histogram" in text
    assert "gateway_ttft_seconds_count 1" in text
    assert "gateway_tokens_per_second_bucket" in text
    assert 'gateway_admitted_total{priority="interactive"} 1' in text


# ---------------------------------------------------------------------------
# Acceptance: concurrent mixed load, bounded queues, exact outcomes
# ---------------------------------------------------------------------------


def test_integration_overload_outcomes_metrics_and_drain():
    """>= 32 concurrent mixed generate/consensus requests against queue
    bounds that force sheds: every request gets exactly one terminal
    outcome (result / 429 / deadline-expired), the metrics series agree
    with the observed outcomes, and graceful drain completes every
    admitted request."""
    handle, client, reg = _boot(
        FakeBackend(latency=0.05),
        admission=AdmissionConfig(
            max_queue=4, max_inflight=2, retry_after_s=0.5
        ),
    )
    outcomes = collections.Counter()
    lock = threading.Lock()

    def one(i):
        kind = ("generate", "consensus", "deadline")[i % 3]
        try:
            if kind == "generate":
                r = client.generate(f"g{i}")
                assert r["text"] == f"Echo: g{i}"
            elif kind == "consensus":
                r = client.consensus(f"c{i}", seed=i)
                assert r["rounds"] >= 1
            else:
                # A deadline tight enough that queued requests expire
                # under the 2-wide in-flight window, loose enough that
                # an immediately-dispatched one can finish.
                r = client.generate(f"d{i}", deadline_s=0.08)
            key = ("ok", kind)
        except GatewayHTTPError as e:
            assert e.status in (429, 504), f"unexpected status {e.status}"
            if e.status == 429:
                assert e.retry_after is not None and e.retry_after > 0
            key = (e.status, kind)
        with lock:
            outcomes[key] += 1

    threads = [threading.Thread(target=one, args=(i,)) for i in range(36)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # Exactly one terminal outcome per request.
    assert sum(outcomes.values()) == 36
    n_ok = sum(v for (s, _), v in outcomes.items() if s == "ok")
    n_shed = sum(v for (s, _), v in outcomes.items() if s == 429)
    n_expired = sum(v for (s, _), v in outcomes.items() if s == 504)
    assert n_ok + n_shed + n_expired == 36
    assert n_ok >= 1
    assert n_shed >= 1, f"queue bound never forced a shed: {outcomes}"

    snap = reg.snapshot()

    def tot(prefix):
        return sum(v for k, v in snap.items() if k.startswith(prefix))

    # Metrics agree with observed outcomes.
    assert tot("gateway_shed_total") == n_shed
    assert tot("gateway_deadline_expired_total") == n_expired
    assert tot("gateway_admitted_total") == 36 - n_shed
    # Every admitted request reached a terminal outcome.
    assert tot("gateway_completed_total") == tot("gateway_admitted_total")
    # TTFT/latency observed once per successful request; queue gauges
    # are empty at quiescence.
    assert snap["gateway_ttft_seconds_count"] == n_ok
    assert snap["gateway_request_seconds_count"] == n_ok
    assert tot("gateway_queue_depth") == 0
    assert snap.get("gateway_inflight", 0) == 0

    # Phase 2 — graceful drain with admitted work still in flight.
    results = []

    def late(i):
        try:
            results.append(("ok", client.generate(f"late{i}")["text"]))
        except Exception as e:  # noqa: BLE001
            results.append(("err", repr(e)))

    late_threads = [
        threading.Thread(target=late, args=(i,)) for i in range(4)
    ]
    admit_target = tot("gateway_admitted_total") + 4
    for t in late_threads:
        t.start()
    # Poll (not a fixed sleep): all four must be ADMITTED before drain
    # begins, else a slow-to-schedule client thread gets a 503.
    deadline = time.time() + 10
    while time.time() < deadline:
        s2 = reg.snapshot()
        if (
            sum(
                v
                for k, v in s2.items()
                if k.startswith("gateway_admitted_total")
            )
            >= admit_target
        ):
            break
        time.sleep(0.01)
    handle.drain()
    for t in late_threads:
        t.join()
    assert [s for s, _ in results] == ["ok"] * 4, results
    snap = reg.snapshot()
    assert sum(
        v for k, v in snap.items() if k.startswith("gateway_admitted_total")
    ) == sum(
        v for k, v in snap.items() if k.startswith("gateway_completed_total")
    )


# ---------------------------------------------------------------------------
# Repo hygiene: committed eval reports must be real JSON
# ---------------------------------------------------------------------------


def test_committed_eval_reports_are_nonempty_valid_json():
    """Round 5 shipped a 0-byte eval report; the committed measurement
    record must stay parseable."""
    import llm_consensus_tpu.eval as eval_pkg

    reports = sorted(
        (Path(eval_pkg.__file__).parent / "reports").glob("*.json")
    )
    assert reports, "eval/reports/ unexpectedly empty"
    for path in reports:
        raw = path.read_text()
        assert raw.strip(), f"{path.name} is empty"
        json.loads(raw)  # raises on malformed JSON
