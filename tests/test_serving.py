"""Batch scheduler / serving backend tests.

Concurrent submits must coalesce into batched device programs and every
future must resolve (the reference's concurrency model — unbounded
per-request futures, ``src/main.rs:101,156,182`` — has no such layer).
"""

import asyncio

import jax
import pytest

from llm_consensus_tpu.backends.base import GenerationRequest, SamplingParams
from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.serving import (
    BatchScheduler,
    SchedulerConfig,
    ServingBackend,
)


@pytest.fixture(scope="module")
def engine():
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return InferenceEngine(
        cfg,
        params,
        engine_config=EngineConfig(
            max_new_tokens=4, seq_buckets=(16,), batch_buckets=(1, 2, 4, 8)
        ),
    )


def test_concurrent_submits_resolve(engine):
    sched = BatchScheduler(engine, SchedulerConfig(linger_s=0.02))
    try:
        futures = [
            sched.submit(
                GenerationRequest(
                    prompt=f"q{i}", params=SamplingParams(max_new_tokens=4)
                )
            )
            for i in range(8)
        ]
        results = [f.result(timeout=60) for f in futures]
        assert len(results) == 8
        assert all(r.num_tokens >= 1 for r in results)
    finally:
        sched.close()


def test_mixed_sampling_configs_grouped(engine):
    sched = BatchScheduler(engine, SchedulerConfig(linger_s=0.02))
    try:
        f1 = sched.submit(
            GenerationRequest(prompt="a", params=SamplingParams(max_new_tokens=2))
        )
        f2 = sched.submit(
            GenerationRequest(prompt="b", params=SamplingParams(max_new_tokens=4))
        )
        r1, r2 = f1.result(timeout=60), f2.result(timeout=60)
        assert 1 <= r1.num_tokens <= 2
        assert 1 <= r2.num_tokens <= 4
    finally:
        sched.close()


def test_serving_backend_through_consensus(engine):
    """Coordinator protocol over the scheduler-backed Backend seam."""
    from llm_consensus_tpu.consensus.coordinator import (
        Coordinator,
        CoordinatorConfig,
    )
    from llm_consensus_tpu.consensus.personas import default_panel

    sched = BatchScheduler(engine, SchedulerConfig(linger_s=0.02))
    try:
        coord = Coordinator(
            default_panel(),
            ServingBackend(sched),
            CoordinatorConfig(
                max_rounds=2,
                seed=0,
                sampling=SamplingParams(max_new_tokens=4, temperature=0.8),
            ),
        )
        result = asyncio.run(coord.run("Scheduled question?"))
        assert isinstance(result.answer, str)
        assert 1 <= result.rounds <= 2
    finally:
        sched.close()


def test_submit_after_close_raises(engine):
    sched = BatchScheduler(engine, SchedulerConfig())
    sched.close()
    with pytest.raises(RuntimeError):
        sched.submit(GenerationRequest(prompt="late"))
