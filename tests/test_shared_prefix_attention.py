"""Shared-prefix decode attention (PR 3): read common KV once per group.

Parity anchors for the two-phase kernel family
(`ops/pallas/attention.py`: dense bf16, dense int8 head-major, paged
grouped) against the single-pass references — CPU interpret mode,
seeded, including the boundary-page (partially shared) group, a group
that shrinks mid-decode as members retire, and the degenerate 1-member
group. Plus the GroupTracker metadata builder, the batcher's grouped
end-to-end path (text parity + bytes-saved metrics), the engine
N-fanout A/B, and the memory planners' prefix-shared accounting.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models.cache import quantize_kv
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.paged_cache import GroupTracker
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.ops.attention import (
    decode_attention,
    decode_attention_quant,
    decode_attention_shared_prefix,
    decode_attention_shared_prefix_quant,
    merge_decode_partials,
)
from llm_consensus_tpu.ops.pallas.attention import (
    flash_decode_attention_shared_prefix,
    flash_decode_attention_shared_prefix_q8,
    paged_decode_attention_grouped,
)
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)

CFG = get_config("test-tiny")


def _params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _shared_cache(key, b, s, hkv, d, plen):
    """Dense [B, S, Hkv, D] K/V whose slots [0, plen) are identical
    across rows — the shared-prefill invariant."""
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, hkv, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, hkv, d))
    k = k.at[:, :plen].set(k[0, :plen])
    v = v.at[:, :plen].set(v[0, :plen])
    return k, v


# ---------------------------------------------------------------------------
# The LSE merge and the XLA reference
# ---------------------------------------------------------------------------


def test_merge_decode_partials_recombines_split_softmax():
    """Splitting softmax attention at an arbitrary slot and merging the
    (m, l, o) partials must reproduce the single-pass result — the
    identity the whole kernel family rests on."""
    key = jax.random.PRNGKey(0)
    s, d = 24, 8
    scores = jax.random.normal(key, (1, 1, 1, 1, s), jnp.float32) * 4.0
    v = jax.random.normal(jax.random.fold_in(key, 1), (1, s, 1, d))

    def partial_over(mask):
        sc = jnp.where(mask, scores, -1e30)
        m = sc.max(-1, keepdims=True)
        p = jnp.exp(sc - jnp.where(m <= -5e29, 0.0, m))
        l = p.sum(-1, keepdims=True)
        o = jnp.einsum("bkgqs,bskd->bkgqd", p, v) / jnp.maximum(l, 1e-30)
        return m, l, o

    full = partial_over(jnp.ones((s,), bool))[2]
    slot = jnp.arange(s)
    for split in (0, 7, 12, s):
        left = partial_over(slot < split)
        right = partial_over(slot >= split)
        merged = merge_decode_partials(*left, *right)
        np.testing.assert_allclose(
            np.asarray(merged), np.asarray(full), rtol=1e-6, atol=1e-6,
            err_msg=f"split={split}",
        )


def test_xla_reference_matches_plain_decode_attention():
    key = jax.random.PRNGKey(1)
    b, h, hkv, d, s = 4, 4, 2, 32, 40
    q = jax.random.normal(key, (b, 1, h, d), jnp.float32)
    valid = jnp.asarray([20, 40, 17, 33], jnp.int32)
    for plen in (0, 8, 16):
        k, v = _shared_cache(jax.random.fold_in(key, plen), b, s, hkv, d, plen)
        want = decode_attention(q, k, v, valid)
        got = decode_attention_shared_prefix(q, k, v, valid, jnp.int32(plen))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
            err_msg=f"plen={plen}",
        )


# ---------------------------------------------------------------------------
# Dense kernels (CPU interpret)
# ---------------------------------------------------------------------------


def test_flash_shared_prefix_bf16_matches_ungrouped():
    """Grouped output == ungrouped row-kernel reference output, ragged
    valid lengths, including a prefix that ends mid-block and an S the
    block width must divide unevenly (the _sp_block path)."""
    key = jax.random.PRNGKey(2)
    b, h, hkv, d, s = 3, 4, 2, 128, 48  # blk = 48, single S-block
    q = jax.random.normal(key, (b, 1, h, d), jnp.float32)
    valid = jnp.asarray([22, 48, 19], jnp.int32)
    for plen in (0, 16, 18):
        k, v = _shared_cache(jax.random.fold_in(key, plen), b, s, hkv, d, plen)
        want = decode_attention(q, k, v, valid)
        got = flash_decode_attention_shared_prefix(
            q, k, v, valid, jnp.int32(plen), interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
            err_msg=f"plen={plen}",
        )


def test_flash_shared_prefix_bf16_multi_block():
    """Multi-S-block shapes (nblk > 1): the suffix pass SKIPS the block
    the prefix covers (the bandwidth point of the split) and the online
    softmax folds across blocks — parity at a block-aligned prefix, a
    mid-block prefix, and a row whose fill ends mid-block."""
    key = jax.random.PRNGKey(12)
    b, h, hkv, d, s = 2, 4, 2, 128, 256  # blk = 128, nblk = 2
    q = jax.random.normal(key, (b, 1, h, d), jnp.float32)
    valid = jnp.asarray([200, 131], jnp.int32)
    for plen in (128, 100):
        k, v = _shared_cache(jax.random.fold_in(key, plen), b, s, hkv, d, plen)
        want = decode_attention(q, k, v, valid)
        got = flash_decode_attention_shared_prefix(
            q, k, v, valid, jnp.int32(plen), interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
            err_msg=f"plen={plen}",
        )


def test_flash_shared_prefix_q8_matches_quant_reference():
    """int8-KV variant == the dequantizing jnp reference (and the XLA
    shared-prefix quant reference) — MQA edge included (hkv=1)."""
    key = jax.random.PRNGKey(3)
    for hkv in (2, 1):
        b, h, d, s = 3, 4, 64, 32
        plen = 16
        q = jax.random.normal(jax.random.fold_in(key, hkv), (b, 1, h, d))
        k, v = _shared_cache(jax.random.fold_in(key, 7), b, s, hkv, d, plen)
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        # Sequence-major -> head-major QuantKVCache layout. The shared
        # prefix stays identical across rows after quantization (the
        # per-(token, head) scales are row-independent).
        kq, ks = kq.transpose(0, 2, 1, 3), ks.transpose(0, 2, 1)
        vq, vs = vq.transpose(0, 2, 1, 3), vs.transpose(0, 2, 1)
        valid = jnp.asarray([20, 32, 17], jnp.int32)
        want = decode_attention_quant(q, kq, ks, vq, vs, valid)
        ref = decode_attention_shared_prefix_quant(
            q, kq, ks, vq, vs, valid, jnp.int32(plen)
        )
        got = flash_decode_attention_shared_prefix_q8(
            q, kq, ks, vq, vs, valid, jnp.int32(plen), interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(want), rtol=2e-5, atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
            err_msg=f"hkv={hkv}",
        )


# ---------------------------------------------------------------------------
# Paged grouped kernel (CPU interpret)
# ---------------------------------------------------------------------------


def test_paged_grouped_kernel_mixed_membership():
    """Two groups + an ungrouped row + a degenerate 1-member group in
    ONE program, all matching the gather reference. Rows 0/1 share
    pages [7, 2] (two full shared pages, private boundary/suffix pages
    after — the partially-shared admission shape); row 2 is ungrouped;
    row 3 is a 1-member group (must be exact, not just tolerated)."""
    key = jax.random.PRNGKey(4)
    b, h, hkv, d = 4, 4, 2, 128
    n_pages, pg, p_per = 12, 8, 4
    k_pool = jax.random.normal(jax.random.fold_in(key, 1), (n_pages, pg, hkv, d))
    v_pool = jax.random.normal(jax.random.fold_in(key, 2), (n_pages, pg, hkv, d))
    tables = jnp.asarray(
        [[7, 2, 9, 0], [7, 2, 3, 10], [5, 4, 0, 0], [6, 1, 0, 0]]
    )
    valid = jnp.asarray([19, 27, 10, 14], jnp.int32)
    q = jax.random.normal(jax.random.fold_in(key, 3), (b, h, d), jnp.float32)

    gid = jnp.asarray([0, 0, -1, 1], jnp.int32)
    rep = jnp.asarray([0, 3, 0, 0], jnp.int32)
    gpages = jnp.asarray([2, 1, 0, 0], jnp.int32)
    sstart = jnp.asarray([16, 16, 0, 8], jnp.int32)
    got = paged_decode_attention_grouped(
        q, k_pool, v_pool, tables, valid, gid, rep, gpages, sstart,
        interpret=True,
    )
    k_seq = k_pool[tables].reshape(b, p_per * pg, hkv, d)
    v_seq = v_pool[tables].reshape(b, p_per * pg, hkv, d)
    want = decode_attention(q[:, None], k_seq, v_seq, valid)[:, 0]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_paged_grouped_kernel_no_groups_degrades_to_plain():
    """All rows ungrouped (gid -1 everywhere, zero-page groups): the
    grouped program must still equal the plain path — phase 1
    contributes nothing anywhere."""
    key = jax.random.PRNGKey(5)
    b, h, hkv, d = 2, 4, 2, 128
    n_pages, pg, p_per = 8, 8, 3
    k_pool = jax.random.normal(jax.random.fold_in(key, 1), (n_pages, pg, hkv, d))
    v_pool = jax.random.normal(jax.random.fold_in(key, 2), (n_pages, pg, hkv, d))
    tables = jnp.asarray([[4, 1, 0], [2, 6, 0]])
    valid = jnp.asarray([13, 20], jnp.int32)
    q = jax.random.normal(jax.random.fold_in(key, 3), (b, h, d), jnp.float32)
    got = paged_decode_attention_grouped(
        q, k_pool, v_pool, tables, valid,
        jnp.asarray([-1, -1], jnp.int32),
        jnp.zeros((1,), jnp.int32),
        jnp.zeros((1,), jnp.int32),
        jnp.zeros((2,), jnp.int32),
        interpret=True,
    )
    k_seq = k_pool[tables].reshape(b, p_per * pg, hkv, d)
    v_seq = v_pool[tables].reshape(b, p_per * pg, hkv, d)
    want = decode_attention(q[:, None], k_seq, v_seq, valid)[:, 0]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


# ---------------------------------------------------------------------------
# GroupTracker: metadata builder over shared page runs
# ---------------------------------------------------------------------------


def test_group_tracker_lcp_groups_donor_and_mappers():
    """The donor's run extends past the mapped header (its own tail
    pages) — LCP grouping still puts donor + mappers in ONE group with
    the common run as the shared region."""
    t = GroupTracker(max_seqs=8, page_size=16)
    t.add(0, (3, 5, 9, 11))  # donor: header pages 3,5 + private tail
    t.add(1, (3, 5))  # mapper
    t.add(2, (3, 5, 20))  # mapper with its own extra full page
    t.add(3, (7, 8))  # unrelated private run
    arrs = t.arrays()
    assert arrs is not None
    gid = np.asarray(arrs.group_id)
    assert gid[0] == gid[1] == gid[2] != -1
    assert gid[3] == -1
    g = int(gid[0])
    assert int(np.asarray(arrs.group_pages)[g]) == 2  # LCP = pages 3,5
    assert int(np.asarray(arrs.shared_start)[0]) == 32
    assert int(np.asarray(arrs.group_rep)[g]) in (0, 1, 2)
    assert t.largest_group == 3
    assert t.saved_tokens_per_step == 2 * 2 * 16  # (3-1) members * 2pg * 16


def test_group_tracker_shrinks_and_drops_singletons():
    t = GroupTracker(max_seqs=4, page_size=16)
    t.add(0, (1, 2))
    t.add(1, (1, 2))
    assert t.arrays() is not None
    assert t.peak_group == 2
    t.remove(1)  # group shrinks to one member -> no group
    assert t.arrays() is None
    assert t.largest_group == 0
    assert t.peak_group == 2  # high-water mark survives
    t.add(2, ())  # empty run: stays ungrouped, never groups
    assert t.arrays() is None
    t.add(3, (1, 2, 7))
    assert t.arrays() is not None  # seqs 0 and 3 share (1, 2)
    assert int(np.asarray(t.arrays().group_pages)[0]) == 2


def test_group_tracker_caps_group_count():
    t = GroupTracker(max_seqs=8, page_size=4, max_groups=1)
    t.add(0, (1,))
    t.add(1, (1,))
    t.add(2, (2, 3))
    t.add(3, (2, 3))
    t.add(4, (2, 3))
    arrs = t.arrays()
    gid = np.asarray(arrs.group_id)
    # Only the larger group (by members * pages) fits the cap; the
    # other rows stay ungrouped (correct, just undeduped).
    assert (gid != -1).sum() == 3
    assert gid[2] == gid[3] == gid[4] == 0


# ---------------------------------------------------------------------------
# End-to-end: the continuous batcher's grouped decode program
# ---------------------------------------------------------------------------

_HEADER = "Panel shared header for every persona, forty ch: "  # 49 chars
_CCFG = dict(
    max_slots=4,
    page_size=16,
    n_pages=64,
    pages_per_seq=8,
    max_new_tokens=6,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
)


def _serve(batcher, prompts, **kw):
    futs = [batcher.submit(p, **kw) for p in prompts]
    return [f.result(timeout=120) for f in futs]


def test_batcher_grouped_attention_parity_and_metrics():
    """The acceptance criterion end to end: a same-header burst served
    with group-aware decode attention produces IDENTICAL text to the
    ungrouped path, reports shared-KV bytes saved > 0, and exposes the
    group size (the panel's N) — grouped and ungrouped rows coexisting
    in one decode program throughout (slots admit/retire mid-flight)."""
    from llm_consensus_tpu.server.metrics import SHARED_KV_BYTES_SAVED

    params = _params()
    prompts = [_HEADER + f"Q{i}: what is {i}+{i}?" for i in range(4)]

    base = ContinuousBatcher(
        CFG, params,
        config=ContinuousConfig(**_CCFG, prefix_attention=False),
    )
    try:
        want = [r.text for r in _serve(base, prompts)]
        base_stats = base.stats()
    finally:
        base.close()
    # The ungrouped baseline must not count savings.
    assert base_stats["shared_kv_bytes_saved"] == 0

    before = SHARED_KV_BYTES_SAVED.value
    grouped = ContinuousBatcher(
        CFG.with_(use_pallas=True), params,
        config=ContinuousConfig(**_CCFG, prefix_attention=True),
    )
    try:
        got = [r.text for r in _serve(grouped, prompts)]
        stats = grouped.stats()
    finally:
        grouped.close()

    assert got == want
    assert stats["shared_kv_bytes_saved"] > 0
    assert stats["decode_group_peak"] >= 2
    # The Prometheus counter moved by exactly the batcher's own count.
    assert SHARED_KV_BYTES_SAVED.value - before == stats["shared_kv_bytes_saved"]


def test_batcher_grouped_boundary_page_and_shrinking_group():
    """The boundary-page shape (prefix ends mid-page: full pages map,
    the partial page is CoW-copied and stays SUFFIX) with members
    retiring at different steps (different max_new_tokens), so the
    group shrinks mid-decode — every text byte-identical to the
    ungrouped path."""
    params = _params()
    # BOS + 40 chars = 41 ids: 2 full pages of 16 + a 9-token boundary.
    common = "Forty common characters of shared text."
    prompts = [common + " tail one", common + " tail two",
               common + " tail three"]
    caps = [6, 2, 4]  # retire at different decode steps

    def run(cfg, prefix_attention):
        b = ContinuousBatcher(
            cfg, params,
            config=ContinuousConfig(**_CCFG, prefix_attention=prefix_attention),
        )
        try:
            # Serialize the first admission so the boundary content is
            # READY and the CoW copy actually happens for successors.
            out = [_serve(b, [prompts[0]], max_new_tokens=caps[0])[0].text]
            rest = [
                b.submit(p, max_new_tokens=c)
                for p, c in zip(prompts[1:], caps[1:])
            ]
            out += [f.result(timeout=120).text for f in rest]
            return out, b.stats()
        finally:
            b.close()

    want, base_stats = run(CFG, False)
    got, stats = run(CFG.with_(use_pallas=True), True)
    assert got == want
    assert stats["prefix_pages_copied"] >= 1  # the boundary page rode CoW
    assert stats["shared_kv_bytes_saved"] > 0
    assert stats["decode_group_peak"] >= 2


def test_grouped_attention_survives_host_round_trip():
    """GroupTracker × offload (PR 4): a decode group's shared prefix
    cannot be demoted out from under its ACTIVE members (eviction skips
    pages live tables hold — a filler storm during grouped decode must
    leave the group's output untouched), and once the members retire
    and the header DOES demote to host, the next same-header burst
    restores it under fresh page ids and the group RE-FORMS over them
    — text byte-identical to the ungrouped, offload-off path
    throughout."""
    params = _params()
    # Tails stay short: header (49 chars) + tail must fit the largest
    # bucket (64) or truncation cuts the header off the front.
    group_prompts = [_HEADER + f"p{i} talks" for i in range(3)]
    fillers = [
        f"{i} unique filler storm prompt with enough padding text."
        for i in range(6)
    ]
    # Second wave AFTER the group retires: only then is the header
    # registry-only (refcount 1) and actually evictable — four of
    # these concurrently demand the whole 20-page pool, forcing the
    # header's chain to demote before the re-vote.
    fillers2 = [
        f"{i} second wave filler with just as much padding text."
        for i in range(6, 12)
    ]
    revote = [_HEADER + f"r{i} votes" for i in range(3)]
    # Starved pool: 20 usable pages vs a peak concurrent demand of ~17
    # (3 grouped members + 1 filler slot), so the filler storm must
    # recycle registry pages while the group decodes.
    kw = dict(
        max_slots=4,
        page_size=16,
        n_pages=21,
        pages_per_seq=8,
        max_new_tokens=4,
        seq_buckets=(16, 32, 64),
        prefill_chunk=16,
        share_prefix=True,
    )

    def run(cfg, prefix_attention, host_cache_bytes):
        b = ContinuousBatcher(
            cfg, params,
            config=ContinuousConfig(
                **kw,
                prefix_attention=prefix_attention,
                host_cache_bytes=host_cache_bytes,
            ),
        )
        try:
            # Group decodes (mnt 20) WHILE the filler storm churns the
            # pool through the one remaining slot.
            gf = [b.submit(p, max_new_tokens=20) for p in group_prompts]
            ff = [b.submit(p, max_new_tokens=4) for p in fillers]
            texts = [f.result(timeout=120).text for f in gf + ff]
            texts += [
                r.text for r in _serve(b, fillers2, max_new_tokens=4)
            ]
            mid = b.stats()
            if prefix_attention:
                # Scope the lifetime peak to the re-vote round: the
                # worker is idle here (all futures resolved, queue
                # empty), and a fresh peak proves the group RE-FORMED
                # over the restored pages rather than riding round 1's.
                b._groups.peak_group = 0
            texts += [r.text for r in _serve(b, revote)]
            return texts, mid, b.stats()
        finally:
            b.close()

    want, _, _ = run(CFG, False, 0)
    got, mid, stats = run(CFG.with_(use_pallas=True), True, 64 << 20)
    assert got == want
    # The storm really pressured the pool while the group was live —
    # and could not touch the group's own pages (parity above is the
    # proof; rc > 1 pages are not evictable by construction).
    assert mid["prefix_evictions"] > 0
    assert stats["offload_demoted_pages"] > 0
    # The re-vote header came back from the host tier (3 full pages of
    # the 50-id header), and the group re-formed on the restored ids.
    assert stats["offload_restored_pages"] >= 3
    assert stats["decode_group_peak"] >= 2
    assert stats["free_pages"] == stats["total_pages"]


# ---------------------------------------------------------------------------
# Engine N-fanout path
# ---------------------------------------------------------------------------


def test_engine_fanout_shared_prefix_attention_parity():
    """generate(shared_prefill=True) with the two-phase kernel on vs
    off: identical greedy tokens (the bf16 dense variant; the q8
    variant is kernel-gated to single-device and covered above)."""
    from llm_consensus_tpu.engine.generate import generate

    cfg = CFG.with_(use_pallas=True)
    params = _params()
    b, s = 4, 16
    tokens = jnp.tile(jnp.arange(5, 5 + s, dtype=jnp.int32)[None], (b, 1))
    lengths = jnp.full((b,), s, jnp.int32)
    temps = jnp.full((b,), 0.9, jnp.float32)
    key = jax.random.PRNGKey(11)
    on = generate(
        cfg, params, tokens, lengths, key, temps, max_new_tokens=6,
        eos_id=-1, shared_prefill=True, shared_prefix_attention=True,
    )
    off = generate(
        cfg, params, tokens, lengths, key, temps, max_new_tokens=6,
        eos_id=-1, shared_prefill=True, shared_prefix_attention=False,
    )
    np.testing.assert_array_equal(np.asarray(on.tokens), np.asarray(off.tokens))
    np.testing.assert_allclose(
        np.asarray(on.logprob_sum), np.asarray(off.logprob_sum),
        rtol=1e-4, atol=1e-4,
    )


# ---------------------------------------------------------------------------
# Memory planners: prefix-shared KV accounting
# ---------------------------------------------------------------------------


def test_memory_estimate_accounts_for_shared_prefix():
    from llm_consensus_tpu.engine.engine import (
        EngineConfig, InferenceEngine, plan_memory,
    )

    eng = InferenceEngine(
        CFG, _params(),
        engine_config=EngineConfig(max_new_tokens=8, seq_buckets=(16, 32)),
    )
    full = eng.memory_estimate(n_candidates=4, prompt_len=16)
    deduped = eng.memory_estimate(
        n_candidates=4, prompt_len=16, shared_prefix_len=16
    )
    # prefix stored once instead of once per row: (b-1) * s token-slots
    # of KV come off the estimate, everything else unchanged.
    per_token = CFG.n_layers * CFG.n_kv_heads * 2 * CFG.head_dim * 2
    assert full["kv_cache_bytes"] - deduped["kv_cache_bytes"] == (
        (full["batch"] - 1) * 16 * per_token
    )
    assert deduped["params_bytes"] == full["params_bytes"]
    # Over-asking caps at the prompt bucket (suffixes never share).
    capped = eng.memory_estimate(
        n_candidates=4, prompt_len=16, shared_prefix_len=10_000
    )
    assert capped["kv_cache_bytes"] == deduped["kv_cache_bytes"]

    # plan_memory (config-only) agrees with the instantiated estimate.
    plan = plan_memory(
        CFG, n_candidates=4, prompt_len=16, new_tokens=8,
        seq_buckets=(16, 32), shared_prefix_len=16,
    )
    assert plan["kv_cache_bytes"] == deduped["kv_cache_bytes"]
