"""Speculative decoding (engine/speculative.py + decode_chunk).

The invariant that makes speculation safe: greedy speculative output ==
vanilla greedy decode output token-for-token, for ANY draft model — a
good draft only changes speed (acceptance rate), never the tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.generate import generate
from llm_consensus_tpu.engine.speculative import speculative_generate
from llm_consensus_tpu.models.cache import KVCache
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import (
    decode_chunk,
    decode_step,
    init_params,
    prefill,
)

CFG = get_config("test-tiny")


def _params(seed):
    return init_params(CFG, jax.random.PRNGKey(seed), dtype=jnp.float32)


def _prompt_batch():
    tokens = jnp.asarray(
        [[5, 6, 7, 8, 9, 0, 0, 0], [10, 11, 12, 13, 14, 15, 16, 17]],
        jnp.int32,
    )
    lengths = jnp.asarray([5, 8], jnp.int32)
    return tokens, lengths


# ---------------------------------------------------------------------------
# decode_chunk: the verification op
# ---------------------------------------------------------------------------


def test_decode_chunk_matches_sequential_decode_steps():
    """Chunk logits == the logits of K sequential decode_steps."""
    params = _params(0)
    tokens, lengths = _prompt_batch()
    K = 3
    chunk_tokens = jnp.asarray([[21, 22, 23], [24, 25, 26]], jnp.int32)

    cache = KVCache.create(CFG, 2, 32, dtype=jnp.float32)
    _, cache = prefill(CFG, params, tokens, lengths, cache)
    seq_logits = []
    c = cache
    for i in range(K):
        lg, c = decode_step(CFG, params, chunk_tokens[:, i : i + 1], c)
        seq_logits.append(lg)
    want = jnp.stack(seq_logits, axis=1)  # [B, K, V]

    cache2 = KVCache.create(CFG, 2, 32, dtype=jnp.float32)
    _, cache2 = prefill(CFG, params, tokens, lengths, cache2)
    got, cache2 = decode_chunk(CFG, params, chunk_tokens, cache2)

    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    # Length is NOT advanced by the chunk itself.
    assert cache2.length.tolist() == lengths.tolist()


def test_decode_chunk_rejects_quant_cache():
    from llm_consensus_tpu.models.cache import QuantKVCache

    params = _params(0)
    cache = QuantKVCache.create(CFG, 1, 16)
    with pytest.raises(ValueError, match="bf16"):
        decode_chunk(CFG, params, jnp.ones((1, 2), jnp.int32), cache)


# ---------------------------------------------------------------------------
# speculative_generate: exactness vs vanilla greedy
# ---------------------------------------------------------------------------


def _vanilla_greedy(params, tokens, lengths, max_new):
    out = generate(
        CFG,
        params,
        tokens,
        lengths,
        jax.random.PRNGKey(0),
        jnp.zeros((tokens.shape[0],)),  # temperature 0 = greedy
        max_new_tokens=max_new,
        eos_id=-1,
    )
    return out.tokens


@pytest.mark.parametrize("k_spec", [1, 2, 4])
def test_speculative_equals_greedy_self_draft(k_spec):
    """Draft == target: every draft token accepted, output identical."""
    params = _params(0)
    tokens, lengths = _prompt_batch()
    want = _vanilla_greedy(params, tokens, lengths, 12)
    out = speculative_generate(
        CFG, params, CFG, params, tokens, lengths,
        max_new_tokens=12, k_spec=k_spec, eos_id=-1,
    )
    assert out.tokens.tolist() == want.tolist()
    assert out.num_tokens.tolist() == [12, 12]
    # Self-draft: acceptance is total except the final round, where the
    # max_new_tokens budget can truncate the emitted run.
    assert int(out.accepted) > 0
    b = tokens.shape[0]
    assert int(out.accepted) >= int(out.drafted) - k_spec * b


def test_speculative_equals_greedy_bad_draft():
    """A DIFFERENT random draft: low acceptance, output still exact."""
    params_t = _params(0)
    params_d = _params(99)  # unrelated draft weights
    tokens, lengths = _prompt_batch()
    want = _vanilla_greedy(params_t, tokens, lengths, 10)
    out = speculative_generate(
        CFG, params_t, CFG, params_d, tokens, lengths,
        max_new_tokens=10, k_spec=4, eos_id=-1,
    )
    assert out.tokens.tolist() == want.tolist()
    assert out.num_tokens.tolist() == [10, 10]
    # Bad draft: more rounds than the self-draft case, not more than one
    # emitted token minimum per round.
    assert int(out.rounds) <= 10


def test_speculative_eos_stops_row():
    """EOS inside an accepted run truncates that row's output."""
    params = _params(0)
    tokens, lengths = _prompt_batch()
    ref = _vanilla_greedy(params, tokens, lengths, 12)
    # Pick the token the model actually emits at step 3 of row 0 as the
    # "EOS" so the speculative run must stop there.
    eos = int(ref[0, 3])
    out = speculative_generate(
        CFG, params, CFG, params, tokens, lengths,
        max_new_tokens=12, k_spec=4, eos_id=eos,
    )
    n0 = int(out.num_tokens[0])
    assert n0 <= 4
    assert int(out.tokens[0, n0 - 1]) == eos
    # Tokens past EOS are pad.
    assert all(int(t) == 0 for t in out.tokens[0, n0:])
