"""Speculative decoding (engine/speculative.py + decode_chunk).

The invariant that makes speculation safe: greedy speculative output ==
vanilla greedy decode output token-for-token, for ANY draft model — a
good draft only changes speed (acceptance rate), never the tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.generate import generate
from llm_consensus_tpu.engine.speculative import speculative_generate
from llm_consensus_tpu.models.cache import KVCache
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import (
    decode_chunk,
    decode_step,
    init_params,
    prefill,
)

CFG = get_config("test-tiny")


def _params(seed):
    return init_params(CFG, jax.random.PRNGKey(seed), dtype=jnp.float32)


def _prompt_batch():
    tokens = jnp.asarray(
        [[5, 6, 7, 8, 9, 0, 0, 0], [10, 11, 12, 13, 14, 15, 16, 17]],
        jnp.int32,
    )
    lengths = jnp.asarray([5, 8], jnp.int32)
    return tokens, lengths


# ---------------------------------------------------------------------------
# decode_chunk: the verification op
# ---------------------------------------------------------------------------


def test_decode_chunk_matches_sequential_decode_steps():
    """Chunk logits == the logits of K sequential decode_steps."""
    params = _params(0)
    tokens, lengths = _prompt_batch()
    K = 3
    chunk_tokens = jnp.asarray([[21, 22, 23], [24, 25, 26]], jnp.int32)

    cache = KVCache.create(CFG, 2, 32, dtype=jnp.float32)
    _, cache = prefill(CFG, params, tokens, lengths, cache)
    seq_logits = []
    c = cache
    for i in range(K):
        lg, c = decode_step(CFG, params, chunk_tokens[:, i : i + 1], c)
        seq_logits.append(lg)
    want = jnp.stack(seq_logits, axis=1)  # [B, K, V]

    cache2 = KVCache.create(CFG, 2, 32, dtype=jnp.float32)
    _, cache2 = prefill(CFG, params, tokens, lengths, cache2)
    got, cache2 = decode_chunk(CFG, params, chunk_tokens, cache2)

    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    # Length is NOT advanced by the chunk itself.
    assert cache2.length.tolist() == lengths.tolist()


def test_decode_chunk_accepts_quant_cache():
    """The chunk path now supports the int8 head-major cache (prefix
    caching on kv_quant engines): logits come back finite and the
    returned cache keeps the quant layout. Numerics bound vs bf16 lives
    in test_engine.test_chunk_mode_quant_cache_close_to_bf16."""
    from llm_consensus_tpu.models.cache import QuantKVCache

    params = _params(0)
    cache = QuantKVCache.create(CFG, 1, 16)
    logits, new_cache = decode_chunk(
        CFG, params, jnp.ones((1, 2), jnp.int32), cache
    )
    assert logits.shape == (1, 2, CFG.vocab_size)
    assert bool(jnp.isfinite(logits).all())
    assert isinstance(new_cache, QuantKVCache)
    assert new_cache.k_q.dtype == jnp.int8


# ---------------------------------------------------------------------------
# speculative_generate: exactness vs vanilla greedy
# ---------------------------------------------------------------------------


def _vanilla_greedy(params, tokens, lengths, max_new):
    out = generate(
        CFG,
        params,
        tokens,
        lengths,
        jax.random.PRNGKey(0),
        jnp.zeros((tokens.shape[0],)),  # temperature 0 = greedy
        max_new_tokens=max_new,
        eos_id=-1,
    )
    return out.tokens


@pytest.mark.parametrize("k_spec", [1, 2, 4])
def test_speculative_equals_greedy_self_draft(k_spec):
    """Draft == target: every draft token accepted, output identical."""
    params = _params(0)
    tokens, lengths = _prompt_batch()
    want = _vanilla_greedy(params, tokens, lengths, 12)
    out = speculative_generate(
        CFG, params, CFG, params, tokens, lengths,
        max_new_tokens=12, k_spec=k_spec, eos_id=-1,
    )
    assert out.tokens.tolist() == want.tolist()
    assert out.num_tokens.tolist() == [12, 12]
    # Self-draft: acceptance is total except the final round, where the
    # max_new_tokens budget can truncate the emitted run.
    assert int(out.accepted) > 0
    b = tokens.shape[0]
    assert int(out.accepted) >= int(out.drafted) - k_spec * b


def test_speculative_equals_greedy_bad_draft():
    """A DIFFERENT random draft: low acceptance, output still exact."""
    params_t = _params(0)
    params_d = _params(99)  # unrelated draft weights
    tokens, lengths = _prompt_batch()
    want = _vanilla_greedy(params_t, tokens, lengths, 10)
    out = speculative_generate(
        CFG, params_t, CFG, params_d, tokens, lengths,
        max_new_tokens=10, k_spec=4, eos_id=-1,
    )
    assert out.tokens.tolist() == want.tolist()
    assert out.num_tokens.tolist() == [10, 10]
    # Bad draft: more rounds than the self-draft case, not more than one
    # emitted token minimum per round.
    assert int(out.rounds) <= 10


def test_speculative_eos_stops_row():
    """EOS inside an accepted run truncates that row's output."""
    params = _params(0)
    tokens, lengths = _prompt_batch()
    ref = _vanilla_greedy(params, tokens, lengths, 12)
    # Pick the token the model actually emits at step 3 of row 0 as the
    # "EOS" so the speculative run must stop there.
    eos = int(ref[0, 3])
    out = speculative_generate(
        CFG, params, CFG, params, tokens, lengths,
        max_new_tokens=12, k_spec=4, eos_id=eos,
    )
    n0 = int(out.num_tokens[0])
    assert n0 <= 4
    assert int(out.tokens[0, n0 - 1]) == eos
    # Tokens past EOS are pad.
    assert all(int(t) == 0 for t in out.tokens[0, n0:])


# ---------------------------------------------------------------------------
# Chunked prefill (bounded-memory long-context prefill over decode_chunk)
# ---------------------------------------------------------------------------


def test_prefill_chunked_matches_prefill():
    from llm_consensus_tpu.models.transformer import prefill_chunked

    params = _params(0)
    tokens, lengths = _prompt_batch()

    cache_a = KVCache.create(CFG, 2, 32, dtype=jnp.float32)
    logits_a, cache_a = prefill(CFG, params, tokens, lengths, cache_a)
    cache_b = KVCache.create(CFG, 2, 32, dtype=jnp.float32)
    logits_b, cache_b = prefill_chunked(
        CFG, params, tokens, lengths, cache_b, chunk=3
    )

    np.testing.assert_allclose(
        np.asarray(logits_b), np.asarray(logits_a), rtol=2e-4, atol=2e-4
    )
    assert cache_b.length.tolist() == cache_a.length.tolist()
    # Cache contents agree on every VALID slot (garbage differs on pads).
    for row, n in enumerate(lengths.tolist()):
        np.testing.assert_allclose(
            np.asarray(cache_b.k[:, row, :n]),
            np.asarray(cache_a.k[:, row, :n]),
            rtol=2e-4, atol=2e-4,
        )


def test_prefill_chunked_then_decode_matches():
    """Greedy decode from a chunk-prefilled cache == from one-shot."""
    from llm_consensus_tpu.models.transformer import prefill_chunked

    params = _params(0)
    tokens, lengths = _prompt_batch()

    cache_a = KVCache.create(CFG, 2, 32, dtype=jnp.float32)
    logits_a, cache_a = prefill(CFG, params, tokens, lengths, cache_a)
    cache_b = KVCache.create(CFG, 2, 32, dtype=jnp.float32)
    logits_b, cache_b = prefill_chunked(
        CFG, params, tokens, lengths, cache_b, chunk=5
    )
    ta = jnp.argmax(logits_a, -1).astype(jnp.int32)
    tb = jnp.argmax(logits_b, -1).astype(jnp.int32)
    assert ta.tolist() == tb.tolist()
    for _ in range(4):
        la, cache_a = decode_step(CFG, params, ta[:, None], cache_a)
        lb, cache_b = decode_step(CFG, params, tb[:, None], cache_b)
        ta = jnp.argmax(la, -1).astype(jnp.int32)
        tb = jnp.argmax(lb, -1).astype(jnp.int32)
        assert ta.tolist() == tb.tolist()


def test_engine_speculative_matches_engine_greedy():
    """Engine-level speculative texts == engine greedy texts."""
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine

    params_t = _params(0)
    params_d = _params(5)
    ecfg = EngineConfig(
        max_new_tokens=8, seq_buckets=(16,), batch_buckets=(1, 2)
    )
    eng = InferenceEngine(
        CFG, params_t, engine_config=ecfg, draft=(CFG, params_d)
    )
    prompts = ["the quick brown", "hello there"]
    want = [r.text for r in eng.generate_texts(prompts)]  # temp 0 greedy
    got = [r.text for r in eng.generate_texts_speculative(prompts)]
    assert got == want


def test_engine_speculative_requires_draft():
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(
        CFG, _params(0),
        engine_config=EngineConfig(seq_buckets=(16,), batch_buckets=(1,)),
    )
    with pytest.raises(ValueError, match="draft"):
        eng.generate_texts_speculative(["x"])


# ---------------------------------------------------------------------------
# Sampled speculative decoding (Leviathan acceptance)
# ---------------------------------------------------------------------------


def test_leviathan_accept_marginal_equals_target():
    """Monte Carlo: draft-sample -> accept/residual has marginal p."""
    from llm_consensus_tpu.engine.speculative import leviathan_accept

    p = jnp.asarray([0.4, 0.1, 0.05, 0.2, 0.05, 0.1, 0.05, 0.05])
    q = jnp.asarray([0.1, 0.4, 0.05, 0.05, 0.2, 0.05, 0.1, 0.05])
    n = 40000

    def one(k):
        kd, ka = jax.random.split(k)
        d = jax.random.categorical(kd, jnp.log(q))
        accept, corr = leviathan_accept(p, q, d, ka)
        return jnp.where(accept, d, corr)

    keys = jax.random.split(jax.random.PRNGKey(0), n)
    outs = jax.jit(jax.vmap(one))(keys)
    freq = jnp.bincount(outs, length=8) / n
    # 3-sigma binomial bound per bucket (max p=0.4 -> sigma ~ 0.0024).
    np.testing.assert_allclose(
        np.asarray(freq), np.asarray(p), atol=0.01
    )


def test_sampled_speculative_greedy_limit_exact():
    """temperature=0 rows through the sampled path == greedy output."""
    params_t = _params(0)
    params_d = _params(99)
    tokens, lengths = _prompt_batch()
    want = _vanilla_greedy(params_t, tokens, lengths, 10)
    out = speculative_generate(
        CFG, params_t, CFG, params_d, tokens, lengths,
        max_new_tokens=10, k_spec=3, eos_id=-1,
        temperature=jnp.zeros((2,)), key=jax.random.PRNGKey(4),
    )
    assert out.tokens.tolist() == want.tolist()


def test_sampled_speculative_smoke_and_variety():
    """temperature=1: rows fill their budget; different keys differ."""
    params_t = _params(0)
    params_d = _params(1)
    tokens, lengths = _prompt_batch()
    outs = []
    for seed in (0, 1, 2):
        out = speculative_generate(
            CFG, params_t, CFG, params_d, tokens, lengths,
            max_new_tokens=8, k_spec=3, eos_id=-1,
            temperature=jnp.full((2,), 1.0), key=jax.random.PRNGKey(seed),
        )
        assert out.num_tokens.tolist() == [8, 8]
        outs.append(tuple(map(tuple, out.tokens.tolist())))
    assert len(set(outs)) > 1  # sampling actually varies by key


def test_sampled_speculative_requires_key():
    params = _params(0)
    tokens, lengths = _prompt_batch()
    with pytest.raises(ValueError, match="PRNG key"):
        speculative_generate(
            CFG, params, CFG, params, tokens, lengths,
            max_new_tokens=4, temperature=jnp.ones((2,)),
        )


def test_engine_speculative_chunks_past_batch_bucket():
    """More prompts than the largest batch bucket run as chunks."""
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(
        CFG, _params(0),
        engine_config=EngineConfig(
            max_new_tokens=3, seq_buckets=(16,), batch_buckets=(1, 2)
        ),
        draft=(CFG, _params(5)),
    )
    results = eng.generate_texts_speculative([f"q{i}" for i in range(5)])
    assert len(results) == 5
    assert all(r.num_tokens >= 1 for r in results)


def test_decode_chunk_sliding_window_matches_sequential():
    """Windowed (Mistral-style) chunk decode == sequential decode_steps."""
    cfg_w = CFG.with_(sliding_window=4)
    params = _params(0)
    tokens, lengths = _prompt_batch()
    chunk_tokens = jnp.asarray([[21, 22, 23], [24, 25, 26]], jnp.int32)

    cache = KVCache.create(cfg_w, 2, 32, dtype=jnp.float32)
    _, cache = prefill(cfg_w, params, tokens, lengths, cache)
    seq_logits = []
    c = cache
    for i in range(3):
        lg, c = decode_step(cfg_w, params, chunk_tokens[:, i : i + 1], c)
        seq_logits.append(lg)
    want = jnp.stack(seq_logits, axis=1)

    cache2 = KVCache.create(cfg_w, 2, 32, dtype=jnp.float32)
    _, cache2 = prefill(cfg_w, params, tokens, lengths, cache2)
    got, _ = decode_chunk(cfg_w, params, chunk_tokens, cache2)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_speculative_equals_greedy_sliding_window():
    """Speculative decode stays exact on a windowed config."""
    cfg_w = CFG.with_(sliding_window=4)
    params_t = init_params(cfg_w, jax.random.PRNGKey(0), dtype=jnp.float32)
    params_d = init_params(cfg_w, jax.random.PRNGKey(9), dtype=jnp.float32)
    tokens, lengths = _prompt_batch()
    want = generate(
        cfg_w, params_t, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros((2,)), max_new_tokens=8, eos_id=-1,
    ).tokens
    out = speculative_generate(
        cfg_w, params_t, cfg_w, params_d, tokens, lengths,
        max_new_tokens=8, k_spec=3, eos_id=-1,
    )
    assert out.tokens.tolist() == want.tolist()


# ---------------------------------------------------------------------------
# Mesh (dp/tp) speculative decoding
# ---------------------------------------------------------------------------


def test_speculative_mesh_exactness():
    """Sharded speculative output == single-device speculative output ==
    single-device greedy — batch over `data`, params over `model` (the
    round-4 verdict's last open sharded-parity item)."""
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh
    from llm_consensus_tpu.parallel.partitioning import shard_params

    params_t = _params(0)
    params_d = _params(5)
    tokens, lengths = _prompt_batch()
    # 4 rows so dp=2 actually splits the batch.
    tokens = jnp.concatenate([tokens, tokens[:, ::-1]], axis=0)
    lengths = jnp.concatenate([lengths, lengths], axis=0)

    plain = speculative_generate(
        CFG, params_t, CFG, params_d, tokens, lengths,
        max_new_tokens=8, k_spec=3, eos_id=-1,
    )
    mesh = make_mesh(MeshConfig(data=4, model=2))
    out = speculative_generate(
        CFG,
        shard_params(params_t, mesh),
        CFG,
        shard_params(params_d, mesh),
        tokens,
        lengths,
        max_new_tokens=8,
        k_spec=3,
        eos_id=-1,
        mesh=mesh,
    )
    np.testing.assert_array_equal(
        np.asarray(out.tokens), np.asarray(plain.tokens)
    )
    np.testing.assert_array_equal(
        np.asarray(out.num_tokens), np.asarray(plain.num_tokens)
    )
    # Greedy anchor: speculative == vanilla greedy on the mesh too.
    want = generate(
        CFG, params_t, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros((4,), jnp.float32), max_new_tokens=8, eos_id=-1,
    )
    np.testing.assert_array_equal(
        np.asarray(out.tokens), np.asarray(want.tokens)
    )


def test_engine_speculative_on_mesh_matches_single_device():
    """Engine-level: mesh engine with a draft == plain engine texts."""
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    params_t = _params(0)
    params_d = _params(5)
    ecfg = EngineConfig(
        max_new_tokens=8, seq_buckets=(16,), batch_buckets=(1, 2, 4)
    )
    plain = InferenceEngine(
        CFG, params_t, engine_config=ecfg, draft=(CFG, params_d)
    )
    mesh = make_mesh(MeshConfig(data=4, model=2))
    sharded = InferenceEngine(
        CFG,
        params_t,
        engine_config=ecfg,
        draft=(CFG, params_d),
        mesh=mesh,
    )
    prompts = ["the quick brown", "hello there", "tpu", "mesh check"]
    want = [r.text for r in plain.generate_texts_speculative(prompts)]
    got = [r.text for r in sharded.generate_texts_speculative(prompts)]
    assert got == want
