"""Request-scoped tracing + step-time telemetry (PR 5).

Covers the trace subsystem end to end: bounded Tracer/TraceStore rings
with drop counters mirrored into the metrics registry (lockstep), the
contextvars span protocol, Prometheus label escaping (incl. the newline
case that corrupts an exposition), the gateway acceptance path — one
``POST /v1/consensus`` trace id whose span tree covers admission →
prefill → decode → every consensus round, retrievable at
``GET /debug/traces?id=...`` — liveness/readiness splitting with a
wedged serving loop, the X-Profile device-trace bridge, the
metrics-drift CI gate, and the ``bench.py --serve-trace-overhead``
< 2% A/B leg.
"""

import json
import re
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from llm_consensus_tpu.backends.fake import FakeBackend
from llm_consensus_tpu.server.client import GatewayClient, GatewayHTTPError
from llm_consensus_tpu.server.gateway import (
    Gateway,
    GatewayConfig,
    GatewayThread,
)
from llm_consensus_tpu.server.metrics import (
    REGISTRY,
    TRACE_DROPPED,
    MetricsRegistry,
    _label_str,
)
from llm_consensus_tpu.utils import tracing

ROOT = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Prometheus label escaping (satellite: the newline corruption fix)
# ---------------------------------------------------------------------------


def test_label_escaping_newline_backslash_quote_roundtrip():
    raw = 'line1\nline2 "quoted" back\\slash'
    rendered = _label_str((("reason", raw),))
    # Single physical line — an unescaped \n would split the sample
    # line and corrupt the whole exposition.
    assert "\n" not in rendered
    assert rendered == (
        '{reason="line1\\nline2 \\"quoted\\" back\\\\slash"}'
    )
    # Round-trip through the escaping rules recovers the original.
    inner = rendered[len('{reason="') : -len('"}')]
    unescaped = (
        inner.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )
    assert unescaped == raw


def test_registry_render_survives_newline_label():
    reg = MetricsRegistry()
    c = reg.counter("weird_total")
    c.labels(reason="a\nb").inc()
    c.labels(reason="plain").inc(2)
    lines = reg.render().splitlines()
    samples = [l for l in lines if l.startswith("weird_total{")]
    assert len(samples) == 2  # one line per child, nothing split
    assert 'weird_total{reason="a\\nb"} 1' in samples


# ---------------------------------------------------------------------------
# Bounded rings + drop-counter lockstep (satellite)
# ---------------------------------------------------------------------------


def _dropped(kind: str) -> float:
    return TRACE_DROPPED.labels(kind=kind).value


def test_tracer_ring_cap_and_drop_counter():
    before = _dropped("span")
    tr = tracing.Tracer(max_records=4)
    for i in range(10):
        with tr.span("s", i=i):
            pass
    assert len(tr.records) == 4
    assert tr.dropped == 6
    # Evict-oldest: the survivors are the newest four.
    assert [r.meta["i"] for r in tr.records] == [6, 7, 8, 9]
    assert _dropped("span") - before == 6


def test_trace_store_bounds_traces_and_spans():
    span_before = _dropped("span")
    trace_before = _dropped("trace")
    store = tracing.TraceStore(max_traces=2, max_spans=3)
    t1 = store.start("a")
    t2 = store.start("b")
    for i in range(5):
        t2.add_span(f"s{i}", time.perf_counter(), 0.001)
    assert t2.n_spans == 3
    assert t2.dropped_spans == 2
    assert _dropped("span") - span_before == 2
    t3 = store.start("c")  # evicts t1 (oldest)
    assert store.get(t1.trace_id) is None
    assert store.get(t2.trace_id) is t2
    assert store.get(t3.trace_id) is t3
    assert store.evicted == 1
    assert _dropped("trace") - trace_before == 1
    # Newest-first listing.
    assert [t.name for t in store.traces()] == ["c", "b"]


def test_trace_store_clamps_nonpositive_caps():
    """`serve --trace-max-traces 0` must not turn into a KeyError 500
    on the first request: caps clamp (tracing off = --no-trace)."""
    store = tracing.TraceStore(max_traces=0, max_spans=-5)
    assert store.max_traces == 1 and store.max_spans == 0
    t = store.start("x")
    assert t is not None
    t.add_span("s", time.perf_counter(), 0.001)
    assert t.n_spans == 0 and t.dropped_spans == 1
    store.configure(max_traces=-3)
    assert store.max_traces == 1
    assert store.start("y") is not None  # evicts x, no crash
    assert store.get(t.trace_id) is None


def test_disabled_tracing_is_a_noop():
    store = tracing.TraceStore()
    tracing.set_enabled(False)
    try:
        assert store.start("x") is None
        with tracing.use_trace(None):
            with tracing.request_span("s") as t:
                assert t is None
        assert tracing.current_trace() is None
    finally:
        tracing.set_enabled(True)
    assert len(store) == 0


def test_request_span_nesting_and_cross_thread_attach():
    store = tracing.TraceStore()
    tr = store.start("req")
    with tracing.use_trace(tr):
        assert tracing.current_trace() is tr
        with tracing.request_span("outer"):
            with tracing.request_span("inner"):
                pass
        # A worker thread has no context: it attaches explicitly.
        th = threading.Thread(
            target=lambda: tr.add_span("worker", time.perf_counter(), 0.01)
        )
        th.start()
        th.join()
    assert tracing.current_trace() is None
    tr.finish()
    tree = tr.to_dict()
    top = {n["name"] for n in tree["spans"]}
    assert top == {"outer", "worker"}
    outer = next(n for n in tree["spans"] if n["name"] == "outer")
    assert [c["name"] for c in outer["children"]] == ["inner"]
    assert tree["finished"] is True and tree["duration_s"] > 0


# ---------------------------------------------------------------------------
# Gateway acceptance: one consensus request -> one retrievable span tree
# ---------------------------------------------------------------------------


def _flatten(nodes):
    for n in nodes:
        yield n
        yield from _flatten(n["children"])


def test_consensus_trace_tree_and_derived_histograms():
    """ISSUE 5 acceptance (FakeBackend half): a POST /v1/consensus
    yields one trace id whose span tree covers admission -> prefill ->
    decode -> every consensus round, retrievable at
    GET /debug/traces?id=..., with the derived histograms at
    GET /metrics."""
    # Default (process-wide) registry: the coordinator's histograms and
    # the canonical trace-derived families must ride the SAME /metrics.
    gw = Gateway(FakeBackend(), config=GatewayConfig(port=0))
    handle = GatewayThread(gw).start()
    client = GatewayClient("127.0.0.1", handle.port)
    try:
        r = client.consensus("What is the tallest mountain?", seed=0)
        assert r["rounds"] == 1 and r["endorsed"] is True
        tid = r["trace_id"]
        assert tid
        tree = client.traces(tid)
        assert tree["trace_id"] == tid
        assert tree["finished"] is True
        names = [n["name"] for n in _flatten(tree["spans"])]
        assert "queued" in names and "execute" in names
        rounds = [
            n
            for n in _flatten(tree["spans"])
            if n["name"] == "consensus_round"
        ]
        # Every protocol phase of the 1-round happy path is a span.
        assert {n["meta"]["phase"] for n in rounds} == {
            "propose",
            "evaluate",
        }
        assert names.count("prefill_chunk") >= 1
        assert names.count("decode_step") >= 1
        # The listing knows this trace; unknown ids 404.
        listing = client.traces()
        assert tid in {t["trace_id"] for t in listing["traces"]}
        with pytest.raises(GatewayHTTPError) as e:
            client.traces("deadbeef00000000")
        assert e.value.status == 404
        text = client.metrics()
        assert 'consensus_round_seconds_bucket{phase="evaluate"' in text
        assert "# TYPE gateway_decode_step_seconds histogram" in text
        assert "# TYPE gateway_sched_overhead_seconds histogram" in text
        assert "# TYPE gateway_trace_dropped_total counter" in text
    finally:
        handle.drain()


def test_generate_carries_trace_id_and_stream_done_event():
    gw = Gateway(FakeBackend(), config=GatewayConfig(port=0))
    handle = GatewayThread(gw).start()
    client = GatewayClient("127.0.0.1", handle.port)
    try:
        r = client.generate("trace me")
        tree = client.traces(r["trace_id"])
        assert {n["name"] for n in _flatten(tree["spans"])} >= {
            "queued",
            "execute",
            "prefill_chunk",
            "decode_step",
        }
        events = list(client.stream_generate("stream trace"))
        assert events[-1]["done"] is True
        assert events[-1]["trace_id"]
        client.traces(events[-1]["trace_id"])  # retrievable
    finally:
        handle.drain()


# ---------------------------------------------------------------------------
# Liveness vs readiness (satellite): /healthz stays 200, /readyz flips
# ---------------------------------------------------------------------------


class _StubHealthBackend(FakeBackend):
    def __init__(self):
        super().__init__()
        self.tick_age = 0.0
        self.explode = False

    def health(self):
        if self.explode:
            raise RuntimeError("probe torn down")
        return {
            "alive": True,
            "last_tick_age_s": self.tick_age,
            "last_step_age_s": None,
        }


def test_readyz_flips_on_stale_heartbeat_healthz_stays_live():
    backend = _StubHealthBackend()
    gw = Gateway(
        backend,
        config=GatewayConfig(port=0, ready_stall_s=1.0),
        registry=MetricsRegistry(),
    )
    handle = GatewayThread(gw).start()
    client = GatewayClient("127.0.0.1", handle.port)
    try:
        assert client.readyz()["ready"] is True
        backend.tick_age = 5.0  # loop "wedged"
        with pytest.raises(GatewayHTTPError) as e:
            client.readyz()
        assert e.value.status == 503
        assert "stalled" in e.value.body
        # Liveness is unaffected: the process still answers.
        h = client.healthz()
        assert h["status"] == "ok"
        assert h["backend"]["last_tick_age_s"] == 5.0
        backend.tick_age = 0.0
        assert client.readyz()["ready"] is True
        # A RAISING health probe fails CLOSED (state unknown => 503),
        # while liveness keeps answering with the error recorded.
        backend.explode = True
        with pytest.raises(GatewayHTTPError) as e:
            client.readyz()
        assert e.value.status == 503
        assert "health probe failed" in e.value.body
        assert "error" in client.healthz()["backend"]
    finally:
        handle.drain()


def test_shed_and_drained_requests_do_not_retain_traces():
    """A request rejected at the admission door did no traceable work:
    retaining its trace would let a 429/503 storm churn the bounded
    ring and evict exactly the slow traces being debugged."""
    gw = Gateway(FakeBackend(), config=GatewayConfig(port=0))
    handle = GatewayThread(gw).start()
    client = GatewayClient("127.0.0.1", handle.port)
    store = tracing.trace_store()
    try:
        ok = client.generate("keep this trace")
        gw.admission.begin_drain()
        before = {t.trace_id for t in store.traces(limit=store.max_traces)}
        with pytest.raises(GatewayHTTPError) as e:
            client.generate("shed me")
        assert e.value.status == 503
        after = {t.trace_id for t in store.traces(limit=store.max_traces)}
        assert after == before  # the drained request left no trace
        assert ok["trace_id"] in after
    finally:
        handle.drain()


@pytest.fixture(scope="module")
def tiny_batcher():
    import jax
    import jax.numpy as jnp

    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    batcher = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(
            max_slots=2,
            page_size=4,
            n_pages=64,
            pages_per_seq=16,
            max_new_tokens=8,
            seq_buckets=(16, 32),
            prefill_chunk=8,
        ),
    )
    yield batcher
    batcher.close()


def test_readyz_flips_when_real_batcher_loop_wedges(tiny_batcher):
    """The satellite's stall test: wedge the REAL continuous-batcher
    host loop and watch /readyz flip 503, then recover."""
    from llm_consensus_tpu.serving.continuous import ContinuousBackend

    gw = Gateway(
        ContinuousBackend(tiny_batcher),
        config=GatewayConfig(port=0, ready_stall_s=1.0),
        registry=MetricsRegistry(),
    )
    handle = GatewayThread(gw).start()
    client = GatewayClient("127.0.0.1", handle.port)

    def poll(want_ready, deadline_s=15.0):
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            try:
                if client.readyz()["ready"] is want_ready:
                    return True
            except GatewayHTTPError as e:
                assert e.status == 503
                if not want_ready:
                    return True
            time.sleep(0.1)
        return False

    try:
        assert poll(True), "batcher never reported ready"
        # Wedge: every loop iteration now stalls 3 s in admission (the
        # instance attribute shadows the bound method).
        tiny_batcher._admit = lambda: time.sleep(3.0)
        try:
            assert poll(False), "readyz never flipped to 503"
        finally:
            del tiny_batcher._admit
        assert poll(True), "readyz never recovered after unwedging"
    finally:
        handle.drain()


# ---------------------------------------------------------------------------
# Continuous batcher: spans + stats()/Prometheus lockstep (acceptance)
# ---------------------------------------------------------------------------


def test_batcher_spans_and_stats_metrics_lockstep(tiny_batcher):
    """The real serving half of the acceptance: a traced request's tree
    records prefill chunks and decode steps, and the span-derived
    histograms move in lockstep with the batcher's stats() mirror."""
    step_h = REGISTRY.get("gateway_decode_step_seconds")
    over_h = REGISTRY.get("gateway_sched_overhead_seconds")
    st0 = tiny_batcher.stats()
    h0 = (step_h.count, step_h.sum, over_h.count, over_h.sum)
    trace = tracing.trace_store().start("batcher-req")
    with tracing.use_trace(trace):
        fut = tiny_batcher.submit(
            "a prompt long enough to take several chunks", max_new_tokens=8
        )
    out = fut.result(timeout=300)
    assert out.num_tokens == 8
    names = [s.name for s in trace.spans()]
    assert names.count("prefill_chunk") >= 2  # chunked over the prompt
    assert names.count("decode_step") >= 7  # one per step it decoded in
    st1 = tiny_batcher.stats()
    # stats() moved by exactly what the process-wide histograms moved
    # (this batcher is the only serving activity in this window).
    assert (
        st1["decode_step_seconds_count"] - st0["decode_step_seconds_count"]
        == step_h.count - h0[0]
        >= 7
    )
    assert st1["decode_step_seconds_sum"] - st0[
        "decode_step_seconds_sum"
    ] == pytest.approx(step_h.sum - h0[1])
    assert (
        st1["sched_overhead_seconds_count"]
        - st0["sched_overhead_seconds_count"]
        == over_h.count - h0[2]
        >= 1
    )
    assert st1["sched_overhead_seconds_sum"] - st0[
        "sched_overhead_seconds_sum"
    ] == pytest.approx(over_h.sum - h0[3])
    hb = tiny_batcher.heartbeat()
    assert hb["alive"] is True and hb["last_step_age_s"] is not None


# ---------------------------------------------------------------------------
# X-Profile bridge: a flagged request drops a TensorBoard device trace
# ---------------------------------------------------------------------------


def test_x_profile_writes_device_trace(tmp_path):
    profile_dir = tmp_path / "profiles"
    gw = Gateway(
        FakeBackend(),
        config=GatewayConfig(port=0, profile_dir=str(profile_dir)),
        registry=MetricsRegistry(),
    )
    handle = GatewayThread(gw).start()
    client = GatewayClient("127.0.0.1", handle.port)
    try:
        # Unflagged requests never touch the profiler.
        client.generate("plain")
        assert not profile_dir.exists() or not any(profile_dir.rglob("*"))
        r = client.generate("profile me", headers={"X-Profile": "1"})
        dumped = [p for p in profile_dir.rglob("*") if p.is_file()]
        assert dumped, "X-Profile: 1 produced no device-trace files"
        # The profiled window is marked on the request's host trace.
        tree = client.traces(r["trace_id"])
        names = {n["name"] for n in _flatten(tree["spans"])}
        assert "jax_profile" in names
    finally:
        handle.drain()


# ---------------------------------------------------------------------------
# CI gates: metrics drift + the < 2% tracing-overhead bench leg
# ---------------------------------------------------------------------------


def test_check_metrics_drift_gate_passes():
    r = subprocess.run(
        [sys.executable, "scripts/check_metrics.py"],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert r.returncode == 0, r.stderr


def test_check_metrics_detects_undeclared_family(tmp_path):
    """The gate actually bites: an instrumentation site registering a
    family that metrics.py does not declare fails the check."""
    import shutil

    clone = tmp_path / "repo"
    clone.mkdir()
    (clone / "scripts").mkdir()
    shutil.copy(ROOT / "scripts" / "check_metrics.py", clone / "scripts")
    shutil.copy(ROOT / "README.md", clone / "README.md")
    pkg = clone / "llm_consensus_tpu"
    for rel in (
        "llm_consensus_tpu/__init__.py",
        "llm_consensus_tpu/version.py",
        "llm_consensus_tpu/server/__init__.py",
        "llm_consensus_tpu/server/metrics.py",
        "llm_consensus_tpu/utils/__init__.py",
        "llm_consensus_tpu/utils/tracing.py",
        "llm_consensus_tpu/utils/logging.py",
        "llm_consensus_tpu/serving/continuous.py",
        "llm_consensus_tpu/serving/scheduler.py",
        "llm_consensus_tpu/serving/offload.py",
        "llm_consensus_tpu/serving/flight.py",
        "llm_consensus_tpu/serving/fleet.py",
        "llm_consensus_tpu/serving/fleet_control.py",
        "llm_consensus_tpu/serving/control.py",
        "llm_consensus_tpu/serving/disagg.py",
        "llm_consensus_tpu/serving/remote_store.py",
        "llm_consensus_tpu/serving/modelset.py",
        "llm_consensus_tpu/serving/vocab_align.py",
        "llm_consensus_tpu/server/gateway.py",
        "llm_consensus_tpu/server/admission.py",
        "llm_consensus_tpu/consensus/coordinator.py",
    ):
        dst = clone / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(ROOT / rel, dst)
    (clone / "bench.py").write_text("")
    gw = clone / "llm_consensus_tpu/server/gateway.py"
    gw.write_text(
        gw.read_text()
        + '\n_ROGUE = None  # reg.counter("gateway_rogue_total", "oops")\n'
    )
    r = subprocess.run(
        [sys.executable, "scripts/check_metrics.py"],
        cwd=clone,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert r.returncode == 1
    assert "gateway_rogue_total" in r.stderr


def test_bench_serve_trace_overhead_cpu_ab_leg(tmp_path):
    """ISSUE 5 acceptance: the --serve-trace-overhead A/B leg shows
    < 2% tok/s overhead (paired-median gate) on the CPU smoke, rc 0,
    with the artifact landing atomically at --out."""
    out = tmp_path / "reports" / "trace_ab.json"
    r = subprocess.run(
        [
            sys.executable, "bench.py", "--tiny", "--cpu",
            "--serve-trace-overhead", "--serve-requests", "6",
            "--serve-slots", "2", "--new-tokens", "8",
            "--prompt-len", "64", "--serve-chunk", "1",
            "--serve-prefill-chunk", "64", "--out", str(out),
        ],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=570,
    )
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-2000:])
    payload = json.loads(out.read_text())
    assert payload == json.loads(r.stdout.strip().splitlines()[-1])
    assert payload["value"] > 0
    m = payload["metric"]
    assert "request tracing ON" in m
    assert int(re.search(r"(\d+) spans", m).group(1)) > 0
    # rc 0 means the DUAL gate held (per-leg bests OR paired median) —
    # re-imposing a hard best-ratio floor here re-creates the exact
    # single-estimator flake the dual gate exists to absorb (PR 10
    # measured vs_baseline swinging 0.30..1.14 across clean runs of a
    # throttled box while the paired median stayed well inside 2%).
    assert payload["vs_baseline"] > 0
    assert list(out.parent.glob("*.tmp.*")) == []
