"""Training loop driver (training/loop.py): run, checkpoint, resume.

Resume determinism is the anchor: train 6 steps straight vs train 3 +
"crash" + resume for 3 — identical final params and data order.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh
from llm_consensus_tpu.training.data import TokenBatchLoader, write_token_shard
from llm_consensus_tpu.training.loop import (
    LoopConfig,
    run_training,
    TrainReport,
)
from llm_consensus_tpu.training.train import TrainConfig

CFG = get_config("test-tiny")
TCFG = TrainConfig(warmup_steps=1, total_steps=10, remat=False)


@pytest.fixture
def shard(tmp_path):
    path = tmp_path / "tokens.bin"
    rng = np.random.default_rng(0)
    write_token_shard(path, rng.integers(0, CFG.vocab_size, 4096))
    return path


def _loader(shard, seed=0):
    return TokenBatchLoader(shard, batch=4, seq=16, seed=seed, prefer_native=False)


def _params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def test_loop_runs_and_loss_decreases(shard):
    state, report = run_training(
        CFG,
        TCFG,
        _loader(shard),
        LoopConfig(total_steps=8, log_every=4),
        params=_params(),
    )
    assert report.final_step == 8
    assert len(report.losses) == 2
    assert report.losses[-1].loss < report.losses[0].loss + 0.5
    assert report.losses[-1].tokens_per_sec > 0


def test_loader_seek_reproduces_stream(shard):
    a = _loader(shard)
    batches = [a.next()[0] for _ in range(5)]
    b = _loader(shard)
    b.seek(3)
    assert b.position == 3
    np.testing.assert_array_equal(b.next()[0], batches[3])
    # Seek backwards restarts the stream.
    b.seek(0)
    np.testing.assert_array_equal(b.next()[0], batches[0])


def test_resume_matches_straight_run(shard, tmp_path):
    straight, _ = run_training(
        CFG,
        TCFG,
        _loader(shard),
        LoopConfig(total_steps=6),
        params=_params(),
    )

    ckpt = str(tmp_path / "ckpt")
    run_training(
        CFG,
        TCFG,
        _loader(shard),
        LoopConfig(total_steps=3, ckpt_every=3, ckpt_dir=ckpt),
        params=_params(),
    )
    resumed_state, report = run_training(
        CFG,
        TCFG,
        _loader(shard),  # fresh loader: seek() must restore position
        LoopConfig(total_steps=6, ckpt_every=0, ckpt_dir=ckpt),
        params=_params(),
    )
    assert report.resumed_from == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(straight.params),
        jax.tree_util.tree_leaves(resumed_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        )


def test_loop_on_sharded_mesh(shard, cpu_devices):
    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2), cpu_devices)
    state, report = run_training(
        CFG,
        TCFG,
        _loader(shard),
        LoopConfig(total_steps=4, log_every=2),
        mesh=mesh,
        params=_params(),
    )
    assert report.final_step == 4
    assert all(np.isfinite(e.loss) for e in report.losses)


def test_loop_on_pipeline_mesh(shard, cpu_devices):
    from llm_consensus_tpu.parallel.compat import SUPPORTS_PARTIAL_AUTO

    if not SUPPORTS_PARTIAL_AUTO:
        # data/pipe manual + model auto: the old shard_map's ``auto=``
        # lowering aborts XLA's partitioner (see parallel/compat.py).
        pytest.skip("partial-auto shard_map unsupported on this jax")
    cfg = CFG.with_(n_layers=4)
    mesh = make_mesh(MeshConfig(data=2, pipe=2, model=2), cpu_devices)
    state, report = run_training(
        cfg,
        TCFG,
        _loader(shard),
        LoopConfig(total_steps=3, log_every=3, n_microbatches=2),
        mesh=mesh,
        params=init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32),
    )
    assert report.final_step == 3
    assert all(np.isfinite(e.loss) for e in report.losses)
