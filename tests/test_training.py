"""Training-step tests: loss correctness, update mechanics, sharded step.

The reference trains nothing (SURVEY.md §2); these cover the TPU build's
training subsystem, including the mesh-sharded step that
``__graft_entry__.dryrun_multichip`` exercises.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh
from llm_consensus_tpu.training.train import (
    TrainConfig,
    causal_lm_loss,
    init_train_state,
    make_sharded_train_step,
    make_train_step,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return cfg, params


def _batch(cfg, b=2, s=16, seed=0):
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (b, s), 0, cfg.vocab_size)
    mask = jnp.ones((b, s), jnp.float32)
    return tokens, mask


def test_loss_is_finite_and_near_uniform_at_init(tiny):
    cfg, params = tiny
    tokens, mask = _batch(cfg)
    loss = causal_lm_loss(cfg, params, tokens, mask, remat=False)
    assert np.isfinite(float(loss))
    # Random init ~ uniform over vocab: loss ≈ log(V).
    assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.0


def test_loss_mask_zeroes_positions(tiny):
    cfg, params = tiny
    tokens, mask = _batch(cfg)
    full = causal_lm_loss(cfg, params, tokens, mask)
    half_mask = mask.at[:, 8:].set(0.0)
    half = causal_lm_loss(cfg, params, tokens, half_mask)
    assert float(full) != float(half)
    zero = causal_lm_loss(cfg, params, tokens, jnp.zeros_like(mask))
    assert float(zero) == 0.0


def test_train_step_reduces_loss(tiny):
    cfg, params = tiny
    tcfg = TrainConfig(learning_rate=1e-2, warmup_steps=1, total_steps=50, remat=False)
    # Copy: the jitted step donates its state, which would delete the
    # module-scoped fixture's param buffers for later tests.
    params = jax.tree_util.tree_map(jnp.array, params)
    state = init_train_state(cfg, params, tcfg)
    step = make_train_step(cfg, tcfg)
    tokens, mask = _batch(cfg, b=4, s=16)
    losses = []
    for _ in range(8):
        state, loss = step(state, tokens, mask)
        losses.append(float(loss))
    assert int(state.step) == 8
    assert losses[-1] < losses[0]  # memorizing a fixed batch


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_train_step_matches_unsharded(tiny):
    cfg, params = tiny
    tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=1, total_steps=50, remat=True)
    tokens, mask = _batch(cfg, b=8, s=16)

    # Copy params: the jitted steps donate their input state, which would
    # otherwise delete the buffers shared between both runs.
    copy = lambda t: jax.tree_util.tree_map(jnp.array, t)  # noqa: E731
    ref_state = init_train_state(cfg, copy(params), tcfg)
    ref_step = make_train_step(cfg, tcfg)
    ref_state, ref_loss = ref_step(ref_state, tokens, mask)

    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2))
    state = init_train_state(cfg, copy(params), tcfg)
    step, place = make_sharded_train_step(cfg, tcfg, mesh)
    state, s_tokens, s_mask = place(state, tokens, mask)
    state, loss = step(state, s_tokens, s_mask)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    # Spot-check a param leaf matches after one update.
    np.testing.assert_allclose(
        np.asarray(state.params["norm_f"]),
        np.asarray(ref_state.params["norm_f"]),
        rtol=1e-4,
        atol=1e-5,
    )


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_train_step_moe_expert_parallel():
    cfg = get_config("test-tiny-moe")
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    tcfg = TrainConfig(warmup_steps=1, total_steps=10, remat=False)
    mesh = make_mesh(MeshConfig(data=2, model=2, expert=2))
    step, place = make_sharded_train_step(cfg, tcfg, mesh)
    tokens, mask = _batch(cfg, b=4, s=8, seed=2)
    state = init_train_state(cfg, params, tcfg)
    state, s_tokens, s_mask = place(state, tokens, mask)
    state, loss = step(state, s_tokens, s_mask)
    assert np.isfinite(float(loss))
    assert int(state.step.addressable_shards[0].data) == 1


def test_mixed_precision_compute_dtype():
    """compute_dtype='bfloat16': fp32 masters stay fp32, loss is close
    to the fp32 run, grads/updates land on the masters."""
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens, mask = _batch(cfg, b=4, s=16, seed=3)

    tcfg32 = TrainConfig(warmup_steps=1, total_steps=10, remat=False)
    tcfg16 = TrainConfig(
        warmup_steps=1, total_steps=10, remat=False, compute_dtype="bfloat16"
    )
    def fresh():
        return init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)

    step32 = make_train_step(cfg, tcfg32)
    step16 = make_train_step(cfg, tcfg16)
    s32, loss32 = step32(init_train_state(cfg, fresh(), tcfg32), tokens, mask)
    s32, _ = step32(s32, tokens, mask)  # step 0 has warmup LR 0
    s16, loss16 = step16(init_train_state(cfg, fresh(), tcfg16), tokens, mask)
    s16, _ = step16(s16, tokens, mask)
    assert abs(float(loss32) - float(loss16)) < 0.1
    # Masters keep fp32 dtype and actually moved.
    leaf = s16.params["blocks"]["wq"]
    assert leaf.dtype == jnp.float32
    assert float(jnp.max(jnp.abs(leaf - params["blocks"]["wq"]))) > 0


# ---------------------------------------------------------------------------
# MoE router auxiliary losses
# ---------------------------------------------------------------------------


def test_moe_router_aux_uniform_is_one():
    """Perfectly uniform routing gives load_balance == 1.0."""
    import jax.numpy as jnp

    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import moe_router_aux

    cfg = get_config("test-tiny-moe")
    e = cfg.n_experts
    t = 32
    logits = jnp.zeros((t, e), jnp.float32)  # uniform probs
    # assignments spread evenly over experts
    top_idx = (jnp.arange(t * cfg.n_experts_per_token) % e).reshape(t, -1)
    aux = moe_router_aux(cfg, logits, top_idx)
    assert abs(float(aux["load_balance"]) - 1.0) < 1e-5
    assert float(aux["z_loss"]) >= 0.0


def test_moe_router_aux_collapse_exceeds_one():
    import jax.numpy as jnp

    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import moe_router_aux

    cfg = get_config("test-tiny-moe")
    e = cfg.n_experts
    t = 32
    logits = jnp.zeros((t, e), jnp.float32).at[:, 0].set(10.0)
    top_idx = jnp.zeros((t, cfg.n_experts_per_token), jnp.int32)
    aux = moe_router_aux(cfg, logits, top_idx)
    assert float(aux["load_balance"]) > 1.5  # collapsed routing penalized


def test_moe_aux_loss_enters_training_loss():
    """The MoE training loss moves with the aux weight; dense is inert."""
    import jax
    import jax.numpy as jnp

    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.training.train import causal_lm_loss

    cfg = get_config("test-tiny-moe")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size
    )
    mask = jnp.ones((2, 12), jnp.float32)
    base = causal_lm_loss(
        cfg.with_(moe_aux_loss_weight=0.0, moe_z_loss_weight=0.0),
        params, tokens, mask, remat=False,
    )
    heavy = causal_lm_loss(
        cfg.with_(moe_aux_loss_weight=10.0, moe_z_loss_weight=0.0),
        params, tokens, mask, remat=False,
    )
    assert float(heavy) > float(base)
    # grads flow through the aux term to the router
    g = jax.grad(
        lambda p: causal_lm_loss(
            cfg.with_(moe_aux_loss_weight=10.0), p, tokens, mask,
            remat=False,
        )
    )(params)
    assert float(jnp.max(jnp.abs(g["blocks"]["router"]))) > 0.0
