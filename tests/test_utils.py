"""Tests for logging setup and span tracing."""

import json
import logging

from llm_consensus_tpu.utils.logging import setup_logging
from llm_consensus_tpu.utils.tracing import Tracer


def test_setup_logging_levels():
    setup_logging("debug")
    assert logging.getLogger().level == logging.DEBUG
    setup_logging("warning,llm_consensus_tpu.consensus=debug")
    assert logging.getLogger().level == logging.WARNING
    assert (
        logging.getLogger("llm_consensus_tpu.consensus").level == logging.DEBUG
    )
    setup_logging("bogus-level")  # falls back to info, no crash
    assert logging.getLogger().level == logging.INFO


def test_tracer_spans_and_summary(tmp_path):
    tr = Tracer()
    with tr.span("evaluate", round=1):
        with tr.span("decode"):
            pass
    with tr.span("decode"):
        pass
    assert len(tr.records) == 3
    s = tr.summary()
    assert s["decode"]["count"] == 2
    assert s["evaluate"]["count"] == 1
    assert tr.total("decode") >= 0.0

    out = tmp_path / "trace.json"
    tr.dump_json(str(out))
    data = json.loads(out.read_text())
    assert len(data) == 3
    assert {d["name"] for d in data} == {"evaluate", "decode"}
    assert any(d.get("meta") == {"round": 1} for d in data)
