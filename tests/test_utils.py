"""Tests for logging setup and span tracing."""

import json
import logging

from llm_consensus_tpu.utils.logging import setup_logging
from llm_consensus_tpu.utils.tracing import Tracer


def test_setup_logging_levels():
    setup_logging("debug")
    assert logging.getLogger().level == logging.DEBUG
    setup_logging("warning,llm_consensus_tpu.consensus=debug")
    assert logging.getLogger().level == logging.WARNING
    assert (
        logging.getLogger("llm_consensus_tpu.consensus").level == logging.DEBUG
    )
    setup_logging("bogus-level")  # falls back to info, no crash
    assert logging.getLogger().level == logging.INFO


def test_tracer_spans_and_summary(tmp_path):
    tr = Tracer()
    with tr.span("evaluate", round=1):
        with tr.span("decode"):
            pass
    with tr.span("decode"):
        pass
    assert len(tr.records) == 3
    s = tr.summary()
    assert s["decode"]["count"] == 2
    assert s["evaluate"]["count"] == 1
    assert tr.total("decode") >= 0.0

    out = tmp_path / "trace.json"
    tr.dump_json(str(out))
    data = json.loads(out.read_text())
    assert len(data) == 3
    assert {d["name"] for d in data} == {"evaluate", "decode"}
    assert any(d.get("meta") == {"round": 1} for d in data)


def test_engine_records_generate_spans():
    import jax
    import jax.numpy as jnp

    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.utils.tracing import Tracer

    cfg = get_config("test-tiny")
    tracer = Tracer()
    eng = InferenceEngine(
        cfg,
        init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32),
        engine_config=EngineConfig(
            max_new_tokens=3, seq_buckets=(16,), batch_buckets=(1, 2)
        ),
        tracer=tracer,
    )
    eng.generate_texts(["one", "two"])
    spans = [r for r in tracer.records if r.name == "engine.generate"]
    assert len(spans) == 1
    assert spans[0].meta["n_real"] == 2
    assert spans[0].duration > 0


def test_engine_records_speculative_spans():
    import jax
    import jax.numpy as jnp

    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.utils.tracing import Tracer

    cfg = get_config("test-tiny")
    tracer = Tracer()
    eng = InferenceEngine(
        cfg,
        init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32),
        engine_config=EngineConfig(
            max_new_tokens=3, seq_buckets=(16,), batch_buckets=(1, 2)
        ),
        draft=(cfg, init_params(cfg, jax.random.PRNGKey(7), dtype=jnp.float32)),
        tracer=tracer,
    )
    eng.generate_texts_speculative(["one"])
    spans = [
        r for r in tracer.records if r.name == "engine.generate_speculative"
    ]
    assert len(spans) == 1
    assert spans[0].meta["k_spec"] == 4


def test_earliest_stop_cut_and_tail_window():
    """The shared stop rules (utils/stops): earliest occurrence wins
    across stops; window covers worst-case one-byte-per-token emission
    even when the tokenizer's own encoding is shorter."""
    from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
    from llm_consensus_tpu.utils.stops import (
        earliest_stop_cut,
        stop_tail_window,
    )

    assert earliest_stop_cut("abcdef", ["cd", "ef"]) == 2
    assert earliest_stop_cut("abcdef", ["ef", "cd"]) == 2  # order-free
    assert earliest_stop_cut("abcdef", ["zz"]) == -1
    assert earliest_stop_cut("", ["a"]) == -1

    tok = ByteTokenizer()
    assert stop_tail_window(tok, []) == 0
    # Byte tokenizer: window = byte length + slack.
    assert stop_tail_window(tok, ["\n\n"]) == 2 + 8
    assert stop_tail_window(tok, ["ab", "abcd"]) == 4 + 8

    class MergeTok:
        """Stub merge-based tokenizer: whole string -> one id."""

        def encode(self, s, add_bos=True):
            return [7]

    # Even though the tokenizer encodes the stop as ONE id, a model can
    # emit it one byte-ish token at a time: the byte length must win.
    assert stop_tail_window(MergeTok(), ["Final answer"]) == 12 + 8


def test_visible_id_filter_sizes_window_by_visible_count():
    """VisibleIdFilter: the tail window counts ids that actually decode
    to characters — empty-decoding ids (special/byte-fallback pieces)
    and skip_ids (EOS) must not consume window slots, or a stop
    stretched across them escapes the incremental check (r4 advisor).
    The returned slice stays CONTIGUOUS (empty ids kept, only skip_ids
    removed): byte-fallback fragments decode to nothing alone but
    contribute bytes in context."""
    from llm_consensus_tpu.utils.stops import VisibleIdFilter

    class Tok:
        """ids >= 100 decode to nothing (special pieces); 99 is EOS."""

        eos_id = 99

        def __init__(self):
            self.decode_calls = 0

        def decode(self, ids):
            self.decode_calls += 1
            return "".join(chr(ord("a") + i) for i in ids if i < 99)

    tok = Tok()
    f = VisibleIdFilter(tok, skip_ids=(tok.eos_id,))
    # Window of 2 over a tail full of empty/skip ids extends past them
    # to reach 2 visible tokens; empty ids stay in the slice, EOS out.
    assert f.visible_tail([0, 100, 101, 99, 1], 2) == [0, 100, 101, 1]
    assert f.visible_tail([], 3) == []
    assert f.visible_tail([0, 1, 2], 0) == []
    # Exactly `window` visible ids bound the slice from the left.
    assert f.visible_tail([3, 4, 0, 100, 1], 2) == [0, 100, 1]
    # Memoized: a second pass over the same ids does no new decodes.
    before = tok.decode_calls
    f.visible_tail([0, 100, 101, 99, 1], 2)
    assert tok.decode_calls == before
    # Scan bound: at most 8 * window raw ids are examined, so a
    # pathological all-empty tail degrades (under-covers) instead of
    # scanning the whole history.
    ids = [5] + [100] * 100 + [6]
    assert f.visible_tail(ids, 2) == [100] * 15 + [6]


def test_visible_id_filter_keeps_fragment_assembly():
    """Contiguity matters: a stop character split across byte-fallback
    fragments (each decoding to "" alone) must still assemble in the
    window decode — dropping empty ids would make the stop invisible
    to the incremental check."""
    from llm_consensus_tpu.utils.stops import VisibleIdFilter

    class FragTok:
        """50 and 51 decode to nothing alone; the PAIR decodes to the
        em dash. Other ids are ascii letters."""

        eos_id = 99

        def decode(self, ids):
            out, i = [], 0
            while i < len(ids):
                if ids[i] == 50 and i + 1 < len(ids) and ids[i + 1] == 51:
                    out.append("—")
                    i += 2
                    continue
                if ids[i] not in (50, 51, 99):
                    out.append(chr(ord("a") + ids[i]))
                i += 1
            return "".join(out)

    tok = FragTok()
    f = VisibleIdFilter(tok, skip_ids=(tok.eos_id,))
    tail = f.visible_tail([0, 1, 50, 51, 2], 3)
    assert tail == [0, 1, 50, 51, 2]
    assert "—" in tok.decode(tail)
