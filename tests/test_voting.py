"""Tests for answer aggregation: votes, pooling, on-device reducer.

The reference's only aggregation is the unanimity gate
(``src/main.rs:316-325``), already covered in test_coordinator.py; these
cover the self-consistency generalizations (BASELINE.md configs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.consensus.voting import (
    canonicalize,
    device_majority_vote,
    extract_final_number,
    logit_pool,
    majority_vote,
    self_consistency,
    weighted_vote,
)
from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

# ---------------------------------------------------------------------------
# Canonicalization
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "text,expected",
    [
        ("The answer is 42.", "42"),
        ("#### 1,234", "1234"),
        ("costs $3.50 total", "3.5"),
        ("x = 7.0", "7"),
        ("first 3 then 9", "9"),
        ("Reasoning... #### 12\n", "12"),
        ("negative: -5", "-5"),
        ("no numbers here", None),
    ],
)
def test_extract_final_number(text, expected):
    assert extract_final_number(text) == expected


def test_canonicalize_falls_back_to_text():
    assert canonicalize("  YES  definitely ") == "yes definitely"
    assert canonicalize("answer: 10") == "10"


# ---------------------------------------------------------------------------
# Host-side votes
# ---------------------------------------------------------------------------


def test_majority_vote_basic():
    r = majority_vote(["42", "The answer is 42", "41"])
    assert r.winner == "42"
    assert r.tally == {"42": 2.0, "41": 1.0}
    assert r.n_candidates == 3


def test_majority_vote_representative_text():
    r = majority_vote(["I think 7", "7", "8"])
    assert r.winner == "7"
    assert r.text == "I think 7"  # first raw answer with the winning key


def test_weighted_vote_overrides_count():
    r = weighted_vote(["a", "a", "b"], [1.0, 1.0, 5.0])
    assert r.winner == "b"
    with pytest.raises(ValueError):
        weighted_vote(["a"], [1.0, 2.0])


def test_logit_pool_prefers_mass():
    # Two votes for "1" with tiny probability vs one confident "2".
    r = logit_pool(["1", "1", "2"], [-10.0, -10.0, -0.1])
    assert r.winner == "2"


def test_vote_empty_raises():
    with pytest.raises(ValueError):
        majority_vote([])


# ---------------------------------------------------------------------------
# On-device reducer
# ---------------------------------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_device_majority_vote_matches_host():
    mesh = make_mesh(MeshConfig(data=8))
    ids = jnp.array([3, 1, 3, 2, 3, 1, 0, 1], jnp.int32)  # 3x'3', 3x'1'
    winner, hist = device_majority_vote(ids, n_classes=5, mesh=mesh)
    assert winner == 1  # tie 3 vs 3 -> argmax picks lower id
    np.testing.assert_array_equal(hist, [1, 3, 1, 3, 0])

    w = jnp.array([1, 1, 1, 1, 1, 1, 1, 0.5], jnp.float32)
    winner_w, hist_w = device_majority_vote(
        ids, n_classes=5, mesh=mesh, weights=w
    )
    assert winner_w == 3
    np.testing.assert_allclose(hist_w, [1, 2.5, 1, 3, 0])


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_device_majority_vote_caches_reducer():
    """Repeat votes on the same (mesh, n_classes) must hit the jit cache:
    the reducer is one cached jitted shard_map, not a fresh closure per
    call (a fresh closure keys a new jit cache entry -> recompile)."""
    from llm_consensus_tpu.consensus.voting import _vote_reducer

    mesh = make_mesh(MeshConfig(data=8))
    fn1 = _vote_reducer(mesh, 7, "data")
    fn2 = _vote_reducer(mesh, 7, "data")
    assert fn1 is fn2
    ids = jnp.arange(8, dtype=jnp.int32) % 7
    device_majority_vote(ids, n_classes=7, mesh=mesh)
    n_compiled = fn1._cache_size()
    device_majority_vote(ids, n_classes=7, mesh=mesh)
    assert fn1._cache_size() == n_compiled  # second vote: no new compile
    assert _vote_reducer(mesh, 5, "data") is not fn1  # distinct key


def test_heterogeneous_panel_vote_weights_models():
    """config[3]: candidates from different models vote with their
    model's weight."""
    from llm_consensus_tpu.consensus.voting import heterogeneous_panel_vote

    class Scripted:
        def __init__(self, answer):
            self.answer = answer

        def generate_texts(self, prompts, temperatures=None, seed=0, max_new_tokens=None):
            class R:
                text = self.answer
                num_tokens = 1
                logprob = -1.0

            return [R() for _ in prompts]

    out = heterogeneous_panel_vote(
        {
            "model-a": (Scripted("42"), 1.0),
            "model-b": (Scripted("41"), 3.0),  # heavier model wins
        },
        "What?",
        n_per_model=2,
    )
    assert out.vote.winner == "41"
    assert out.vote.tally == {"42": 2.0, "41": 6.0}
    assert set(out.per_model) == {"model-a", "model-b"}
    assert out.total_tokens == 4


def test_heterogeneous_panel_vote_real_engines():
    from llm_consensus_tpu.consensus.voting import heterogeneous_panel_vote
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    ec = EngineConfig(max_new_tokens=4, seq_buckets=(16,), batch_buckets=(2,))
    engines = {}
    for name in ("test-tiny", "test-tiny-moe"):
        cfg = get_config(name)
        engines[name] = (
            InferenceEngine(
                cfg, init_params(cfg, jax.random.PRNGKey(0)), engine_config=ec
            ),
            1.0,
        )
    out = heterogeneous_panel_vote(engines, "2+2?", n_per_model=2, seed=1)
    assert out.vote.n_candidates == 4
    assert sum(len(v) for v in out.per_model.values()) == 4


# ---------------------------------------------------------------------------
# End-to-end self-consistency on the tiny model
# ---------------------------------------------------------------------------


def test_self_consistency_end_to_end():
    from llm_consensus_tpu.consensus.voting import self_consistency
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(
        cfg,
        params,
        engine_config=EngineConfig(
            max_new_tokens=6, seq_buckets=(16,), batch_buckets=(8,)
        ),
    )
    out = self_consistency(eng, "What is 2+2?", n=8, temperature=1.5, seed=3)
    assert out.vote.n_candidates == 8
    assert len(out.candidates) == 8
    assert out.total_tokens >= 8
    assert out.vote.winner in {canonicalize(c) for c in out.candidates}
    out2 = self_consistency(
        eng, "What is 2+2?", n=8, temperature=1.5, seed=3, method="logit_pool"
    )
    assert out2.vote.n_candidates == 8
    with pytest.raises(ValueError):
        self_consistency(eng, "q", n=2, method="bogus")


def test_self_consistency_device_majority_matches_host():
    """method='device_majority' on a mesh-wired engine: the on-device
    psum+argmax tally picks the same winner as the host vote."""
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    mesh = make_mesh(MeshConfig(data=8))
    ecfg = EngineConfig(
        max_new_tokens=5, seq_buckets=(16,), batch_buckets=(8, 16)
    )
    eng = InferenceEngine(cfg, params, engine_config=ecfg, mesh=mesh)

    host = self_consistency(eng, "2+2?", n=16, temperature=0.9, seed=2)
    dev = self_consistency(
        eng, "2+2?", n=16, temperature=0.9, seed=2,
        method="device_majority",
    )
    assert dev.vote.winner == host.vote.winner
    assert dev.vote.n_candidates == 16
    # Tallies agree as multisets of counts.
    assert sorted(dev.vote.tally.values()) == sorted(
        host.vote.tally.values()
    )


def test_device_majority_requires_mesh_engine():
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    cfg = get_config("test-tiny")
    eng = InferenceEngine(
        cfg,
        init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32),
        engine_config=EngineConfig(seq_buckets=(16,), batch_buckets=(1,)),
    )
    with pytest.raises(ValueError, match="mesh"):
        self_consistency(
            eng, "x", n=1, max_new_tokens=2, method="device_majority"
        )


def test_device_vote_tie_breaks_like_host_vote():
    """On a tied tally both reducers pick the first-seen answer."""
    from types import SimpleNamespace

    from llm_consensus_tpu.consensus.voting import _device_vote

    mesh = make_mesh(MeshConfig(data=8))
    eng = SimpleNamespace(mesh=mesh)
    texts = ["banana", "apple", "banana", "apple"]
    host = majority_vote(texts)
    dev = _device_vote(eng, texts, canonicalize)
    assert dev.winner == host.winner == "banana"


def test_rescore_vote_pools_by_judge_scores():
    """rescore_vote == logit_pool under the judge engine's own scores."""
    import jax

    from llm_consensus_tpu.consensus.voting import logit_pool, rescore_vote
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(8, 16, 32), batch_buckets=(1, 2, 4)
        ),
    )
    answers = ["#### 4", "#### 5", "#### 4 indeed"]
    got = rescore_vote(eng, "Q: 2+2?", answers)
    scores = eng.score_texts("Q: 2+2?", answers, normalize=True)
    want = logit_pool(answers, scores)
    assert got.winner == want.winner
    assert got.tally == want.tally
